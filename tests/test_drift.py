"""Drift observability plane (obs/drift.py): ingest/prediction
sketches, the held-out decay sentinel, the streams shift wrappers
(online/streams.py), and the capsule ``drift.json`` artifact.

The plane's contract is the usual obs one — ``HPNN_DRIFT`` unset ⇒
constant-time no-ops, not one record — plus its own: normalized
``drift.score`` gauges (1.0 = breach) per (detector, kernel) series,
exactly one ``online.drift`` event per rising edge of the breach
bound, and a full reference+live sketch dump in every capture
capsule taken while armed."""

import json
import math
import os

import numpy as np

from hpnn_tpu import obs, serve
from hpnn_tpu.models import kernel as kernel_mod
from hpnn_tpu.obs import drift, triggers
from hpnn_tpu.online import streams
from hpnn_tpu.online.session import OnlineSession


def _read(path):
    with open(path) as fp:
        return [json.loads(ln) for ln in fp if ln.strip()]


def _arm(monkeypatch, tmp_path, window=16, z=3.0):
    sink = tmp_path / "m.jsonl"
    monkeypatch.setenv("HPNN_METRICS", str(sink))
    monkeypatch.setenv("HPNN_DRIFT", "1")
    monkeypatch.setenv("HPNN_DRIFT_WINDOW", str(window))
    monkeypatch.setenv("HPNN_DRIFT_Z", str(z))
    obs._reset_for_tests()
    return sink


def _rows(rng, n, loc=0.0, n_in=4):
    return rng.normal(loc=loc, size=(n, n_in))


# ------------------------------------------------------------ unarmed
def test_unarmed_everything_noops(monkeypatch, tmp_path):
    sink = tmp_path / "m.jsonl"
    monkeypatch.setenv("HPNN_METRICS", str(sink))
    monkeypatch.delenv("HPNN_DRIFT", raising=False)
    obs._reset_for_tests()
    assert not drift.enabled()
    rng = np.random.RandomState(0)
    drift.note_ingest(_rows(rng, 64))
    drift.note_pred("k", _rows(rng, 64))
    drift.note_eval("k", 0.5)
    assert drift.sketch_doc() is None
    assert drift.health_doc() == {"armed": False}
    obs.flush()
    if os.path.exists(sink):
        assert not [r for r in _read(sink)
                    if str(r.get("ev", "")).startswith("drift.")]


def test_config_floor_and_bad_knob_fallback(monkeypatch, tmp_path,
                                            capsys):
    _arm(monkeypatch, tmp_path, window=4)
    cfg = drift._config()
    assert cfg["window"] == drift.WINDOW_FLOOR
    assert cfg["min_rows"] == 8
    monkeypatch.setenv("HPNN_DRIFT_Z", "not-a-number")
    drift._reset_for_tests()
    assert drift._config()["z"] == drift.DEFAULT_Z
    assert "HPNN_DRIFT_Z" in capsys.readouterr().err


# ---------------------------------------------------------------- psi
def test_psi_debiased_null_is_zero_and_shift_breaches():
    ref = np.array([10, 10, 10, 10, 10, 10, 10, 10])
    assert drift._psi(ref, ref) == 0.0  # null clamped by the debias
    moved = np.array([0, 0, 0, 0, 0, 0, 40, 40])
    assert drift._psi(ref, moved) > drift.PSI_BREACH


# ------------------------------------------------------------- ingest
def test_ingest_sketch_detects_covariate_shift(monkeypatch, tmp_path):
    sink = _arm(monkeypatch, tmp_path)
    rng = np.random.RandomState(1)
    drift.note_ingest(_rows(rng, 16))           # freezes the reference
    drift.note_ingest(_rows(rng, 16))           # clean live window
    clean = drift.health_doc()["ingest"]["psi"]
    assert clean is not None and clean < drift.PSI_BREACH
    drift.note_ingest(_rows(rng, 16, loc=5.0))  # shifted live window
    drift.note_ingest(_rows(rng, 16, loc=5.0))  # still over: no re-fire
    obs.flush()
    recs = _read(sink)
    scores = [r for r in recs if r.get("ev") == "drift.score"
              and r.get("detector") == "ingest"]
    assert scores and scores[0]["kernel"] == "stream"
    assert scores[-1]["value"] >= 1.0
    events = [r for r in recs if r.get("ev") == "online.drift"]
    assert len(events) == 1                     # rising edge only
    assert events[0]["detector"] == "ingest"
    assert "ingest:stream" in drift.health_doc()["over"]


def test_ingest_rearms_after_recovery(monkeypatch, tmp_path):
    """Score falling back under the bound re-arms the edge: a second
    shift emits a second online.drift event."""
    sink = _arm(monkeypatch, tmp_path)
    rng = np.random.RandomState(2)
    drift.note_ingest(_rows(rng, 16))
    drift.note_ingest(_rows(rng, 16, loc=5.0))   # first breach
    drift.note_ingest(_rows(rng, 32))            # live ring all clean
    drift.note_ingest(_rows(rng, 16, loc=5.0))   # second breach
    obs.flush()
    events = [r for r in _read(sink) if r.get("ev") == "online.drift"]
    assert len(events) == 2


def test_single_row_feeds_fold_on_the_stride(monkeypatch, tmp_path):
    """Row-at-a-time taps stage until ``_STRIDE`` rows, so the PSI
    recompute and gauge publish never run per request."""
    sink = _arm(monkeypatch, tmp_path)
    rng = np.random.RandomState(3)
    for _ in range(2 * drift._STRIDE):   # reference (16) + live (16)
        drift.note_ingest(_rows(rng, 1))
    obs.flush()
    scores = [r for r in _read(sink) if r.get("ev") == "drift.score"]
    assert len(scores) == 1              # one fold scored, not 16
    for _ in range(drift._STRIDE - 1):
        drift.note_ingest(_rows(rng, 1))
    obs.flush()
    assert len([r for r in _read(sink)
                if r.get("ev") == "drift.score"]) == 1  # still staged


# --------------------------------------------------------------- pred
def test_pred_sketch_detects_class_mix_shift(monkeypatch, tmp_path):
    sink = _arm(monkeypatch, tmp_path)
    rng = np.random.RandomState(4)
    ref = rng.uniform(-1, 0, size=(16, 4))
    ref[:, 0] = 1.0                              # argmax class 0
    drift.note_pred("k", ref)                    # freezes the reference
    live = rng.uniform(-1, 0, size=(16, 4))
    live[:, 2] = 1.0                             # argmax class 2
    drift.note_pred("k", live)
    obs.flush()
    recs = _read(sink)
    shifts = [r for r in recs if r.get("ev") == "drift.pred_shift"]
    assert shifts and shifts[-1]["kernel"] == "k"
    assert shifts[-1]["value"] > drift.PSI_BREACH
    events = [r for r in recs if r.get("ev") == "online.drift"]
    assert [e["detector"] for e in events] == ["pred"]


def test_serve_dispatch_taps_the_pred_sketch(monkeypatch, tmp_path):
    """The real serve path feeds the sketch: enough single infers and
    the kernel's prediction gauges land in the sink."""
    sink = _arm(monkeypatch, tmp_path)
    kern, _ = kernel_mod.generate(7, 8, [5], 2)
    sess = serve.Session(max_batch=8, n_buckets=1, max_wait_ms=0.5)
    try:
        sess.register_kernel("srv", kern)
        rng = np.random.RandomState(5)
        for _ in range(3 * drift._STRIDE):
            sess.infer("srv", rng.normal(size=8))
    finally:
        sess.close()
    obs.flush()
    shifts = [r for r in _read(sink)
              if r.get("ev") == "drift.pred_shift"]
    assert shifts and shifts[-1]["kernel"] == "srv"


# --------------------------------------------------------------- eval
def test_eval_sentinel_warmup_then_decay(monkeypatch, tmp_path):
    sink = _arm(monkeypatch, tmp_path, z=1.5)
    for _ in range(drift._WARMUP + 5):
        drift.note_eval("k", 0.5)       # flat: the sentinel is quiet
    obs.flush()
    recs = _read(sink)
    zs = [r for r in recs if r.get("ev") == "drift.eval_decay"]
    assert len(zs) == drift._WARMUP + 5          # every eval gauged
    assert all(r["value"] == 0.0 for r in zs[:drift._WARMUP])
    assert not [r for r in recs if r.get("ev") == "online.drift"]
    drift.note_eval("k", 5.0)                    # decay step
    obs.flush()
    recs = _read(sink)
    z = [r for r in recs if r.get("ev") == "drift.eval_decay"][-1]
    assert z["value"] > 1.5 and math.isfinite(z["value"])
    events = [r for r in recs if r.get("ev") == "online.drift"]
    assert [e["detector"] for e in events] == ["eval"]
    assert events[0]["kernel"] == "k"
    assert events[0]["score"] >= 1.0


def test_trainer_round_feeds_the_sentinel(monkeypatch, tmp_path):
    """A real online round emits ``online.eval_resident`` every round
    and, armed, the sentinel's ``drift.eval_decay`` gauge."""
    sink = _arm(monkeypatch, tmp_path)
    sess = OnlineSession(rows=16, batch=4, epochs=2, holdout=4,
                         seed=0, start=False,
                         serve_kwargs=dict(max_batch=8, n_buckets=1,
                                           max_wait_ms=0.5))
    try:
        kern, _ = kernel_mod.generate(1, 8, [5], 2)
        sess.add_kernel("k", kern)
        rng = np.random.RandomState(7)
        X = rng.uniform(0.0, 1.0, (48, 8))
        sess.feed(X, np.tanh(X[:, :2]))
        sess.tick()
    finally:
        sess.close()
    obs.flush()
    recs = _read(sink)
    resident = [r for r in recs
                if r.get("ev") == "online.eval_resident"]
    assert resident and resident[-1]["kernel"] == "k"
    assert math.isfinite(resident[-1]["value"])
    assert [r for r in recs if r.get("ev") == "drift.eval_decay"]


# ------------------------------------------------------------ streams
def test_label_shift_wrapper_remaps_targets_only():
    def stream():
        for i in range(8):
            x = np.full(4, float(i))
            t = np.full(3, -1.0)
            t[i % 3] = 1.0
            yield x, t

    plain = list(stream())
    shifted = list(streams.label_shift(stream(), 5, {0: 1, 1: 2, 2: 0}))
    for i, ((xp, tp), (xs, ts)) in enumerate(zip(plain, shifted)):
        assert np.array_equal(xp, xs)            # inputs untouched
        if i < 5:
            assert np.array_equal(tp, ts)
        else:
            assert int(np.argmax(ts)) == (int(np.argmax(tp)) + 1) % 3
    again = list(streams.label_shift(stream(), 5, {0: 1, 1: 2, 2: 0}))
    assert all(np.array_equal(a[1], b[1])
               for a, b in zip(shifted, again))  # deterministic


def test_rotate_wrapper_square_and_phase_roll():
    def stream(n_in):
        for i in range(4):
            x = np.zeros(n_in)
            x[i] = 1.0
            yield x, np.array([1.0])

    # 3x3 square: a 90-degree rotation moves the corner pixel
    out = list(streams.rotate(stream(9), 2, 90.0))
    for i, (x, t) in enumerate(out):
        assert np.array_equal(t, np.array([1.0]))  # targets untouched
        if i < 2:
            assert x[i] == 1.0
    assert not np.array_equal(out[2][0], np.eye(9)[2])
    assert out[2][0].sum() == 1.0                # still one hot pixel
    # non-square: angle/360 of the length as a circular shift
    rolled = list(streams.rotate(stream(10), 0, 36.0))
    assert np.argmax(rolled[0][0]) == 1          # 0 rolled by one slot


# ------------------------------------------------- capsule + health
def test_capture_capsule_carries_drift_json(monkeypatch, tmp_path):
    _arm(monkeypatch, tmp_path)
    monkeypatch.setenv("HPNN_CAPSULE_DIR", str(tmp_path / "caps"))
    monkeypatch.setenv("HPNN_CAPSULE_PROFILE_MS", "0")
    obs._reset_for_tests()
    rng = np.random.RandomState(8)
    drift.note_ingest(_rows(rng, 32))
    man = triggers.capture("manual")
    assert man is not None and "drift.json" in man["files"]
    doc = json.load(open(os.path.join(man["capsule"], "drift.json")))
    assert doc["ingest"]["reference"] and doc["ingest"]["live"]
    assert doc["window"] == drift.WINDOW_FLOOR


def test_capture_without_drift_has_no_artifact(monkeypatch, tmp_path):
    monkeypatch.setenv("HPNN_METRICS", str(tmp_path / "m.jsonl"))
    monkeypatch.delenv("HPNN_DRIFT", raising=False)
    monkeypatch.setenv("HPNN_CAPSULE_DIR", str(tmp_path / "caps"))
    monkeypatch.setenv("HPNN_CAPSULE_PROFILE_MS", "0")
    obs._reset_for_tests()
    man = triggers.capture("manual")
    assert man is not None and "drift.json" not in man["files"]


def test_health_doc_census(monkeypatch, tmp_path):
    _arm(monkeypatch, tmp_path)
    rng = np.random.RandomState(9)
    drift.note_ingest(_rows(rng, 32))
    drift.note_pred("k", rng.normal(size=(32, 4)))
    drift.note_eval("k", 0.5)
    doc = drift.health_doc()
    assert doc["armed"] and doc["window"] == drift.WINDOW_FLOOR
    assert doc["ingest"]["frozen"] and doc["ingest"]["rows_seen"] == 32
    assert "k" in doc["pred"] and "k" in doc["eval"]
    assert doc["psi_breach"] == drift.PSI_BREACH
