"""tools/check_tokens.py — the byte-stability lint, wired as tier-1.

The lint runs a tiny train+eval round with and without ``HPNN_METRICS``
and fails when the stdout token stream differs by a byte (or the sink
misses a tentpole event).  Running it here makes any instrumentation
regression a test failure, not a post-hoc discovery."""

import importlib.util
import os


def _load():
    spec = importlib.util.spec_from_file_location(
        "check_tokens",
        os.path.join(os.path.dirname(__file__), "..", "tools",
                     "check_tokens.py"),
    )
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def test_tokens_byte_stable_under_instrumentation(tmp_path):
    mod = _load()
    failures = mod.check(str(tmp_path))
    assert failures == []


def test_lint_catches_a_perturbed_stream(tmp_path, monkeypatch):
    """The lint must actually bite: a fake obs leak into stdout (or a
    missing sink event) turns into a non-empty failure list."""
    mod = _load()

    real = mod._run_round

    def leaky(tmpdir, metrics_path, probe=None):
        out = real(tmpdir, metrics_path, probe=probe)
        if metrics_path is not None:
            out += '{"ev": "leak", "kind": "event"}\n'
        return out

    monkeypatch.setattr(mod, "_run_round", leaky)
    failures = mod.check(str(tmp_path))
    assert any("byte-identical" in f for f in failures)
