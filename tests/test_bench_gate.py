"""The bench regression gate (tools/bench_gate.py) and the
bench-history trajectory it reads.

The gate is CI surface: exit 0 on a healthy candidate, non-zero on
regression, 2 on usage/IO — asserted through real subprocess runs so
the exit codes are the ones a pipeline would see."""

import importlib.util
import json
import os
import subprocess
import sys

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
GATE = os.path.join(ROOT, "tools", "bench_gate.py")


def _load_gate():
    spec = importlib.util.spec_from_file_location("bench_gate", GATE)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def _entry(slope_xla=100.0, sps=50.0, value=200.0, **extra):
    e = {
        "metric": "samples_per_s", "value": value, "unit": "1/s",
        "batch_sps_median": sps,
        "slope_us_per_step": {"xla": slope_xla, "pallas": slope_xla / 2},
        "serve_p50_ms": 1.0, "serve_p99_ms": 3.0, "serve_rps": 900.0,
        "git_sha": "abc1234", "when": "2026-08-05T12:00:00+0000",
    }
    e.update(extra)
    return e


def _write_history(path, entries):
    with open(path, "w") as fp:
        for e in entries:
            fp.write(json.dumps(e) + "\n")


def _run(args, cwd):
    return subprocess.run(
        [sys.executable, GATE] + args, cwd=cwd,
        capture_output=True, text=True, timeout=120)


# ---------------------------------------------------------- unit level
def test_flatten_and_baseline_median():
    g = _load_gate()
    flat = g.flatten(_entry(slope_xla=100.0))
    assert flat["slope_us_per_step.xla"] == 100.0
    assert flat["slope_us_per_step.pallas"] == 50.0
    assert flat["batch_sps_median"] == 50.0
    assert "git_sha" not in flat and "metric" not in flat
    hist = [_entry(slope_xla=v) for v in (90.0, 100.0, 110.0, 400.0)]
    base = g.baseline(hist, window=3)       # newest 3: 100, 110, 400
    assert base["slope_us_per_step.xla"] == 110.0


def test_gate_directions():
    g = _load_gate()
    base = {"batch_sps_median": 100.0, "slope_us_per_step.xla": 100.0}
    # within tolerance both ways
    assert g.gate({"batch_sps_median": 90.0,
                   "slope_us_per_step.xla": 110.0}, base) == []
    # throughput regresses DOWNWARD ...
    bad = g.gate({"batch_sps_median": 40.0}, base)
    assert len(bad) == 1 and bad[0]["metric"] == "batch_sps_median"
    # ... but a big throughput GAIN is not a regression
    assert g.gate({"batch_sps_median": 500.0}, base) == []
    # slopes regress UPWARD; a faster slope is fine
    assert g.gate({"slope_us_per_step.xla": 10.0}, base) == []
    bad = g.gate({"slope_us_per_step.xla": 200.0}, base)
    assert len(bad) == 1 and bad[0]["ratio"] == 2.0
    # metrics absent from the baseline are skipped, not failed
    assert g.gate({"serve_rps": 1.0}, base) == []


# ------------------------------------------------------ subprocess CLI
def test_gate_passes_on_steady_trajectory(tmp_path):
    hist = tmp_path / "bench_history.jsonl"
    _write_history(hist, [_entry() for _ in range(4)])
    p = _run(["--history", str(hist)], cwd=str(tmp_path))
    assert p.returncode == 0, p.stderr
    assert "PASS" in p.stdout


def test_gate_fails_on_2x_slope_regression(tmp_path):
    """The acceptance case: a synthetic 2x slope_us_per_step
    regression in the candidate must exit non-zero and name the
    metric."""
    hist = tmp_path / "bench_history.jsonl"
    _write_history(hist, [_entry(slope_xla=100.0) for _ in range(4)])
    cand = tmp_path / "cand.json"
    cand.write_text(json.dumps(_entry(slope_xla=200.0)))
    p = _run(["--history", str(hist), "--candidate", str(cand)],
             cwd=str(tmp_path))
    assert p.returncode == 1, (p.stdout, p.stderr)
    assert "FAIL" in p.stdout
    assert "slope_us_per_step.xla" in p.stdout
    # same verdict machine-readably
    p = _run(["--history", str(hist), "--candidate", str(cand),
              "--json"], cwd=str(tmp_path))
    assert p.returncode == 1
    verdict = json.loads(p.stdout)
    assert verdict["pass"] is False
    assert any(r["metric"] == "slope_us_per_step.xla"
               for r in verdict["regressions"])


def test_gate_default_candidate_is_last_history_line(tmp_path):
    hist = tmp_path / "bench_history.jsonl"
    _write_history(hist, [_entry(sps=50.0) for _ in range(3)]
                   + [_entry(sps=5.0)])        # last run collapsed
    p = _run(["--history", str(hist)], cwd=str(tmp_path))
    assert p.returncode == 1
    assert "batch_sps_median" in p.stdout


def test_gate_tolerance_override_and_stdin(tmp_path):
    hist = tmp_path / "bench_history.jsonl"
    _write_history(hist, [_entry(sps=100.0) for _ in range(3)])
    # 20% drop: fails a 10% tolerance, passes a 50% one — via stdin
    cand = json.dumps(_entry(sps=80.0))
    for tol, rc in (("0.1", 1), ("0.5", 0)):
        p = subprocess.run(
            [sys.executable, GATE, "--history", str(hist),
             "--candidate", "-", "--tolerance", tol],
            input=cand, cwd=str(tmp_path),
            capture_output=True, text=True, timeout=120)
        assert p.returncode == rc, (tol, p.stdout, p.stderr)


def test_gate_usage_and_io_errors(tmp_path):
    # missing history file
    p = _run(["--history", str(tmp_path / "nope.jsonl")],
             cwd=str(tmp_path))
    assert p.returncode == 2
    # empty history, no candidate
    hist = tmp_path / "bench_history.jsonl"
    hist.write_text("")
    p = _run(["--history", str(hist)], cwd=str(tmp_path))
    assert p.returncode == 2
    # single entry + no prior baseline = nothing to gate (pass)
    _write_history(hist, [_entry()])
    p = _run(["--history", str(hist)], cwd=str(tmp_path))
    assert p.returncode == 0
    # torn tail line is skipped like obs_report does
    _write_history(hist, [_entry() for _ in range(3)])
    with open(hist, "a") as fp:
        fp.write('{"torn": ')
    p = _run(["--history", str(hist)], cwd=str(tmp_path))
    assert p.returncode == 0, p.stderr
