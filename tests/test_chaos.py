"""Deterministic fault injection (hpnn_tpu/chaos/, docs/resilience.md).

Covers the ``HPNN_CHAOS`` grammar (terms, parameter continuation,
malformed-term degradation), the unset fast path, each action's
behavior at a seam (raise / delay / nan, with ``after``/``times``
budgets), seeded determinism of probabilistic plans, the
``chaos.inject`` audit count, and the memo-reset chain from
``obs.registry._reset_for_tests``.
"""

import contextlib
import json
import os
import time

import numpy as np
import pytest

from hpnn_tpu import chaos, obs
from hpnn_tpu.online import wal as wal_mod


@contextlib.contextmanager
def _armed(plan, seed=None):
    os.environ["HPNN_CHAOS"] = plan
    if seed is not None:
        os.environ["HPNN_CHAOS_SEED"] = str(seed)
    chaos._reset_for_tests()
    try:
        yield
    finally:
        os.environ.pop("HPNN_CHAOS", None)
        os.environ.pop("HPNN_CHAOS_SEED", None)
        chaos._reset_for_tests()


def test_unset_is_disarmed_and_memoized():
    os.environ.pop("HPNN_CHAOS", None)
    chaos._reset_for_tests()
    try:
        assert not chaos.enabled()
        assert chaos.inject("serve.dispatch") is None
        # the verdict is memoized: arming the env AFTER the first read
        # must not change a running process (plans are parsed once)
        os.environ["HPNN_CHAOS"] = "raise@serve.dispatch"
        assert chaos.inject("serve.dispatch") is None
        assert chaos.plan_doc() == []
    finally:
        os.environ.pop("HPNN_CHAOS", None)
        chaos._reset_for_tests()


def test_grammar_parameter_continuation_and_both_separators():
    # the comma inside "ms=5,after=2" is a parameter continuation
    # (no '@'), the semicolon starts a fresh term — one plan, two faults
    with _armed("delay@a.b:ms=5,after=2;raise@c.d:times=3"):
        doc = {d["seam"]: d for d in chaos.plan_doc()}
        assert set(doc) == {"a.b", "c.d"}
        assert doc["a.b"]["action"] == "delay"
        assert doc["a.b"]["ms"] == 5.0
        assert doc["a.b"]["after"] == 2
        assert doc["c.d"]["action"] == "raise"
        assert doc["c.d"]["times"] == 3


def test_malformed_terms_degrade_to_no_fault(capfd):
    # unknown action, empty seam, unknown parameter: each skipped with
    # a stderr warning; the well-formed term still arms
    with _armed("explode@a.b,raise@,delay@x.y:volume=11,raise@c.d"):
        assert chaos.enabled()
        assert [d["seam"] for d in chaos.plan_doc()] == ["c.d"]
        with pytest.raises(chaos.ChaosFault):
            chaos.inject("c.d")
    err = capfd.readouterr().err
    assert err.count("ignoring malformed term") == 3


def test_entirely_malformed_plan_disarms(capfd):
    with _armed("garbage"):
        assert not chaos.enabled()
        assert chaos.inject("anything") is None
    assert "ignoring malformed term" in capfd.readouterr().err


def test_raise_fires_only_at_its_seam():
    with _armed("raise@batcher.submit"):
        assert chaos.inject("serve.dispatch") is None
        assert chaos.inject("batcher.drain", arrays=(np.ones(2),)) is None
        with pytest.raises(chaos.ChaosFault):
            chaos.inject("batcher.submit")


def test_after_skips_then_times_caps():
    with _armed("raise@s.m:after=2,times=1"):
        assert chaos.inject("s.m") is None  # call 1: skipped
        assert chaos.inject("s.m") is None  # call 2: skipped
        with pytest.raises(chaos.ChaosFault):
            chaos.inject("s.m")             # call 3: fires
        assert chaos.inject("s.m") is None  # budget spent
        doc = chaos.plan_doc()[0]
        assert (doc["calls"], doc["fired"]) == (4, 1)


def test_nan_corrupts_a_copy_not_the_originals():
    with _armed("nan@train.round:times=1"):
        a, b = np.ones(3), np.ones((2, 2))
        out = chaos.inject("train.round", arrays=(a, b))
        assert isinstance(out, tuple) and len(out) == 2
        assert np.isnan(out[0][0]) and np.isfinite(out[0][1:]).all()
        assert np.isfinite(out[1]).all()
        # the caller's arrays are untouched — the seam substitutes
        assert np.isfinite(a).all() and np.isfinite(b).all()
        # times=1: the second candidate passes clean
        assert chaos.inject("train.round", arrays=(a, b)) is None


def test_delay_sleeps_the_configured_ms():
    with _armed("delay@s.m:ms=30"):
        t0 = time.perf_counter()
        assert chaos.inject("s.m") is None
        assert time.perf_counter() - t0 >= 0.02


def test_probabilistic_plan_replays_identically(capfd):
    def pattern():
        fired = []
        for _ in range(24):
            try:
                chaos.inject("s.m")
                fired.append(0)
            except chaos.ChaosFault:
                fired.append(1)
        return fired

    with _armed("raise@s.m:p=0.5", seed=3):
        first = pattern()
    with _armed("raise@s.m:p=0.5", seed=3):
        assert pattern() == first
    with _armed("raise@s.m:p=0.5", seed=4):
        other = pattern()
    assert 0 < sum(first) < 24  # actually probabilistic
    assert other != first       # and actually seeded
    capfd.readouterr()  # swallow the firing lines


def test_fire_emits_audit_count_and_stderr(tmp_path, capfd):
    sink = str(tmp_path / "sink.jsonl")
    obs.configure(sink)
    try:
        with _armed("raise@serve.dispatch"):
            with pytest.raises(chaos.ChaosFault):
                chaos.inject("serve.dispatch")
    finally:
        obs.configure(None)
    with open(sink) as fp:
        recs = [json.loads(ln) for ln in fp if ln.strip()]
    hits = [r for r in recs if r.get("ev") == "chaos.inject"]
    assert len(hits) == 1
    assert hits[0]["seam"] == "serve.dispatch"
    assert hits[0]["action"] == "raise"
    assert "raise@serve.dispatch firing" in capfd.readouterr().err


def test_obs_reset_chains_the_chaos_and_wal_memos():
    from hpnn_tpu.obs import registry as obs_registry

    with _armed("raise@s.m"):
        assert chaos.enabled()
        wal_mod.from_env()  # memoize the (disarmed) WAL verdict too
        assert wal_mod._wal is not None
        obs_registry._reset_for_tests()
        assert chaos._plan is None
        assert wal_mod._wal is None
