"""DBG_TRACE / ALLOC_REPORT parity aids (utils/debug.py).

Reference: DBG_TRACE prints '#DBG: acc=%.15f' (include/libhpnn/ann.h:
29-33); ALLOC_REPORT accumulates bytes and ann_kernel_allocate reports
'[CPU] ANN total allocation: %lu (bytes)' at NN_OUT (src/ann.c:190-200,
common.h:245-248).
"""

import numpy as np

from hpnn_tpu.models import kernel as kernel_mod
from hpnn_tpu.utils import debug, logging as log


def test_dbg_trace_token(capsys):
    log.set_verbose(3)
    arr = np.array([1.5, -0.25, 2.0])
    acc = debug.dbg_trace(arr)
    assert acc == 3.25
    out = capsys.readouterr().out
    assert "NN(DBG): #DBG: acc=3.250000000000000\n" in out
    # silent below debug verbosity, value still returned
    log.set_verbose(2)
    assert debug.dbg_trace(arr) == 3.25
    assert capsys.readouterr().out == ""


def test_trace_kernel_layer_order(capsys):
    log.set_verbose(3)
    k, _ = kernel_mod.generate(3, 4, [3], 2)
    accs = debug.trace_kernel(k.weights)
    assert accs == tuple(float(np.sum(w)) for w in k.weights)
    assert capsys.readouterr().out.count("#DBG: acc=") == 2


def test_alloc_report_tokens(capsys):
    import jax.numpy as jnp

    log.set_verbose(3)
    k, _ = kernel_mod.generate(3, 4, [3], 2)
    dev = tuple(jnp.asarray(w) for w in k.weights)
    total = debug.alloc_report(k.weights, dev)
    assert total == sum(w.nbytes for w in k.weights)
    out = capsys.readouterr().out
    assert f"NN: [CPU] ANN total allocation: {total} (bytes)\n" in out
    assert "NN(DBG): [CPU] layer 1 allocation:" in out
    # CPU devices: no accelerator line
    assert out.count("ANN total allocation") == 1


def test_alloc_report_in_driver(tmp_path, capsys):
    """-vv training prints the allocation line (ref: src/ann.c:197)."""
    from tests.test_batch import _conf
    from hpnn_tpu.train import driver

    log.set_verbose(2)
    conf = _conf(tmp_path, n=2)
    assert driver.train_kernel(conf)
    out = capsys.readouterr().out
    assert "NN: [CPU] ANN total allocation:" in out
