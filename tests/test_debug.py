"""DBG_TRACE / ALLOC_REPORT parity aids (utils/debug.py).

Reference: DBG_TRACE prints '#DBG: acc=%.15f' (include/libhpnn/ann.h:
29-33); ALLOC_REPORT accumulates bytes and ann_kernel_allocate reports
'[CPU] ANN total allocation: %lu (bytes)' at NN_OUT (src/ann.c:190-200,
common.h:245-248).
"""

import numpy as np

from hpnn_tpu.models import kernel as kernel_mod
from hpnn_tpu.utils import debug, logging as log


def test_dbg_trace_token(capsys):
    log.set_verbose(3)
    arr = np.array([1.5, -0.25, 2.0])
    acc = debug.dbg_trace(arr)
    assert acc == 3.25
    out = capsys.readouterr().out
    assert "NN(DBG): #DBG: acc=3.250000000000000\n" in out
    # silent below debug verbosity, value still returned
    log.set_verbose(2)
    assert debug.dbg_trace(arr) == 3.25
    assert capsys.readouterr().out == ""


def test_trace_kernel_layer_order(capsys):
    log.set_verbose(3)
    k, _ = kernel_mod.generate(3, 4, [3], 2)
    accs = debug.trace_kernel(k.weights)
    assert accs == tuple(float(np.sum(w)) for w in k.weights)
    assert capsys.readouterr().out.count("#DBG: acc=") == 2


def test_alloc_report_tokens(capsys):
    import jax.numpy as jnp

    log.set_verbose(3)
    k, _ = kernel_mod.generate(3, 4, [3], 2)
    dev = tuple(jnp.asarray(w) for w in k.weights)
    total = debug.alloc_report(k.weights, dev)
    assert total == sum(w.nbytes for w in k.weights)
    out = capsys.readouterr().out
    assert f"NN: [CPU] ANN total allocation: {total} (bytes)\n" in out
    assert "NN(DBG): [CPU] layer 1 allocation:" in out
    # CPU devices: no accelerator line
    assert out.count("ANN total allocation") == 1


def test_alloc_report_at_kernel_generate(tmp_path, capsys):
    """-vv conf load prints the allocation line once, at the
    reference's site — kernel allocation during generate/load
    (ref: src/ann.c:197 via ann_generate/ann_load) — and the train/run
    drivers add none (ref: _NN(run,kernel) allocates no kernel,
    src/libhpnn.c:1306-1536)."""
    from hpnn_tpu import config
    from hpnn_tpu.train import driver

    log.set_verbose(2)
    (tmp_path / "samples").mkdir()
    from tests.test_batch import _write_samples

    _write_samples(tmp_path / "samples", 2)
    (tmp_path / "nn.conf").write_text(
        "[name] t\n[type] ANN\n[init] generate\n[seed] 1\n"
        "[input] 8\n[hidden] 6\n[output] 2\n[train] BP\n"
        f"[sample_dir] {tmp_path}/samples\n[test_dir] {tmp_path}/samples\n"
    )
    conf = config.load_conf(str(tmp_path / "nn.conf"))
    out = capsys.readouterr().out
    assert out.count("NN: [CPU] ANN total allocation:") == 1
    assert driver.train_kernel(conf)
    driver.run_kernel(conf)
    out = capsys.readouterr().out
    # drivers print no HOST line (a [TPU] device line is legitimate)
    assert "[CPU] ANN total allocation" not in out


def test_load_kernel_reports_alloc(tmp_path, capsys):
    """The load path reports at the same site as generate
    (ref: ann_load -> ann_kernel_allocate -> src/ann.c:197)."""
    from hpnn_tpu import config
    from hpnn_tpu.config import NNConf, NNType

    log.set_verbose(2)
    k, _ = kernel_mod.generate(3, 4, [3], 2)
    with open(tmp_path / "k.txt", "w") as fp:
        kernel_mod.dump("t", k, fp)
    capsys.readouterr()
    conf = NNConf(type=NNType.ANN, f_kernel=str(tmp_path / "k.txt"))
    assert config.load_kernel(conf)
    out = capsys.readouterr().out
    assert out.count("NN: [CPU] ANN total allocation:") == 1


def test_lnn_refusal(tmp_path, capsys):
    """LNN is declared but refused by generate/load kernel dispatch
    (ref: src/libhpnn.c:975-980,992-995) — an LNN conf can never
    train."""
    from hpnn_tpu import config
    from hpnn_tpu.config import NNConf, NNType

    log.set_verbose(0)
    conf = NNConf(type=NNType.LNN)
    assert not config.generate_kernel(conf, 4, [3], 2)
    assert conf.kernel is None
    conf.f_kernel = "whatever.txt"
    assert not config.load_kernel(conf)
    # conf-level: an LNN [type] with [init] generate fails to load
    (tmp_path / "nn.conf").write_text(
        "[name] t\n[type] LNN\n[init] generate\n[seed] 1\n"
        "[input] 4\n[hidden] 3\n[output] 2\n[train] BP\n"
        "[sample_dir] s\n[test_dir] s\n"
    )
    assert config.load_conf(str(tmp_path / "nn.conf")) is None
