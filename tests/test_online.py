"""Train-while-serve: the online-learning subsystem
(hpnn_tpu/online/, docs/online.md).

Covers the stream buffer (ring/reservoir/holdout, fake clocks), the
promotion gate (sentinel / margin / eval rejections, atomic install,
bitwise rollback, the post-promotion regression watch), fleet-wise
candidate training, the ``POST /ingest`` HTTP route and loadgen
``--mix``, the registry's ``(st_mtime_ns, st_size)`` staleness
signature, the ``check_obs_catalog --online`` schema lint, and the
acceptance E2E: an MNIST-stream kernel ingesting under live loadgen
traffic promotes a sentinel-clean candidate (version bump +
``online.promote``), improves on held-out eval, and rejects an
injected-NaN candidate while serving continues.

Promotion-race guarantee (ISSUE satellite): a client racing a
promotion sees the old version's answer or the new version's answer,
bitwise — never a torn mix — and rollback restores bitwise-identical
answers.
"""

import http.client
import importlib.util
import json
import os
import threading
import time

import numpy as np
import pytest

from hpnn_tpu import obs, online, serve
from hpnn_tpu.models import kernel as kernel_mod
from hpnn_tpu.online import promote as promote_mod
from hpnn_tpu.online import streams
from hpnn_tpu.online.ingest import SampleBuffer
from hpnn_tpu.serve.registry import Registry, RegistryError
from hpnn_tpu.serve.server import make_server

ROOT = os.path.abspath(os.path.join(os.path.dirname(__file__), ".."))


def _load_tool(name):
    spec = importlib.util.spec_from_file_location(
        name, os.path.join(ROOT, "tools", f"{name}.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def _read(path):
    with open(path) as fp:
        return [json.loads(ln) for ln in fp if ln.strip()]


class FakeClock:
    def __init__(self):
        self.t = 0.0

    def __call__(self):
        return self.t

    def advance(self, dt):
        self.t += dt


def _kernel(seed=7, n_in=8, hidden=(5,), n_out=2):
    k, _ = kernel_mod.generate(seed, n_in, list(hidden), n_out)
    return k


def _stream_block(n, seed=3, n_in=8, n_out=2):
    """A learnable synthetic stream block: targets a smooth function
    of the inputs, so training from a random init reliably improves."""
    rng = np.random.RandomState(seed)
    X = rng.uniform(0.0, 1.0, size=(n, n_in))
    return X, np.tanh(X[:, :n_out])


def _mk_osess(**kw):
    defaults = dict(
        serve_kwargs=dict(max_batch=8, n_buckets=2, max_wait_ms=1.0),
        rows=16, batch=8, epochs=4, interval_s=60.0, holdout=4,
        gate=online.Gate(margin=0.0, watch_s=30.0), seed=5)
    defaults.update(kw)
    return online.OnlineSession(**defaults)


def _tick_until_promoted(osess, max_ticks=6):
    for _ in range(max_ticks):
        summary = osess.tick()
        if summary["promoted"]:
            return summary
    raise AssertionError(f"no promotion within {max_ticks} rounds")


def _weights_of(osess, name):
    return tuple(np.asarray(w)
                 for w in osess.serve.registry.get(name).kernel.weights)


# ======================================================== SampleBuffer
def test_buffer_ring_drop_staleness_fake_clock():
    clock = FakeClock()
    buf = SampleBuffer(capacity=4, holdout=0, clock=clock)
    assert buf.staleness_s() is None
    X, T = _stream_block(6)
    buf.feed(X[:2], T[:2])
    clock.advance(3.0)
    assert buf.feed(X[2:], T[2:]) == 4
    assert buf.depth() == 4                  # ring holds the newest 4
    assert buf.dropped_total() == 2          # the two oldest evicted
    assert buf.total_fed() == 6
    assert buf.widths() == (8, 2)
    assert buf.staleness_s() == 0.0
    clock.advance(1.5)
    assert buf.staleness_s() == pytest.approx(1.5)
    # the snapshot is the newest rows, as copies
    Xs, Ts, meta = buf.snapshot(4)
    assert np.array_equal(Xs, X[2:]) and np.array_equal(Ts, T[2:])
    assert meta["rows"] == 4 and meta["replay"] == 0
    assert meta["staleness_s"] == pytest.approx(1.5)
    Xs[0, 0] = 99.0                          # mutating a copy is safe
    assert buf.snapshot(4)[0][0, 0] == X[2, 0]
    with pytest.raises(ValueError):
        buf.snapshot(5)


def test_buffer_holdout_diverted_never_trained():
    buf = SampleBuffer(capacity=64, holdout=3)
    X, T = _stream_block(9, seed=1)
    buf.feed(X, T)
    assert buf.holdout_depth() == 3          # every 3rd diverted
    assert buf.depth() == 6                  # ... and NOT in the ring
    Xh, Th = buf.eval_snapshot()
    assert Xh.shape == (3, 8) and Th.shape == (3, 2)
    assert np.array_equal(Xh[0], X[2])       # samples 3, 6, 9 (1-based)
    Xs, _, _ = buf.snapshot(6)
    for row in Xh:                           # holdout rows never train
        assert not any(np.array_equal(row, r) for r in Xs)


def test_buffer_reservoir_replay_and_width_pinning():
    buf = SampleBuffer(capacity=8, reservoir=6, holdout=0, seed=0)
    X, T = _stream_block(40, seed=2)
    buf.feed(X, T)
    Xs, _, meta = buf.snapshot(8, replay_frac=0.5)
    assert meta["replay"] == 4               # oldest half swapped
    assert Xs.shape == (8, 8)
    # the newest half is still the ring tail, in order
    assert np.array_equal(Xs[4:], X[-4:])
    with pytest.raises(ValueError):
        buf.feed(np.zeros((2, 5)), np.zeros((2, 2)))   # width mismatch
    with pytest.raises(ValueError):
        buf.feed(np.zeros((2, 8)), np.zeros((3, 2)))   # row mismatch


# ============================================ registry staleness (sig)
def test_registry_sig_catches_sub_second_rewrite(tmp_path):
    path = tmp_path / "kernel.opt"
    with open(path, "w") as fp:
        kernel_mod.dump("t", _kernel(seed=1), fp)
    reg = Registry()
    e0 = reg.load("k", str(path))
    assert e0.sig == (os.stat(path).st_mtime_ns, os.stat(path).st_size)
    assert reg.maybe_reload("k") is False
    # rewrite, then pin the mtime ONE NANOSECOND later: the float
    # st_mtime collapses to the same double, so the old float compare
    # cannot see this rewrite — the ns signature can
    with open(path, "w") as fp:
        kernel_mod.dump("t", _kernel(seed=2), fp)
    ns = e0.sig[0] + 1
    os.utime(path, ns=(ns, ns))
    assert os.stat(path).st_mtime == e0.mtime   # float is blind...
    assert reg.maybe_reload("k") is True        # ...the sig is not
    assert reg.get("k").version == 1


def test_registry_sig_size_catches_equal_timestamp_rewrite(tmp_path):
    path = tmp_path / "kernel.opt"
    path.write_text("x" * 10)
    reg = Registry()
    reg.register("k", _kernel(seed=1), path=str(path),
                 mtime=os.stat(path).st_mtime,
                 sig=(os.stat(path).st_mtime_ns,
                      os.stat(path).st_size))
    st0 = os.stat(path)
    with open(path, "w") as fp:
        kernel_mod.dump("t", _kernel(seed=2), fp)   # different size
    os.utime(path, ns=(st0.st_mtime_ns, st0.st_mtime_ns))
    st1 = os.stat(path)
    assert st1.st_mtime_ns == st0.st_mtime_ns   # timestamp identical
    assert st1.st_size != st0.st_size
    assert reg.maybe_reload("k") is True


def test_registry_pre_sig_entry_falls_back_to_float_mtime(tmp_path):
    path = tmp_path / "kernel.opt"
    with open(path, "w") as fp:
        kernel_mod.dump("t", _kernel(seed=1), fp)
    reg = Registry()
    e = reg.register("k", _kernel(seed=1), path=str(path),
                     mtime=os.stat(path).st_mtime)   # sig=None
    assert e.sig is None
    assert reg.maybe_reload("k") is False        # same float mtime
    os.utime(path, (e.mtime + 10, e.mtime + 10))
    assert reg.maybe_reload("k") is True


def test_registry_install_bumps_version_and_keeps_disk_wins(tmp_path):
    path = tmp_path / "kernel.opt"
    with open(path, "w") as fp:
        kernel_mod.dump("t", _kernel(seed=1), fp)
    reg = Registry()
    e0 = reg.load("k", str(path))
    e1 = reg.install("k", _kernel(seed=2))
    assert e1.version == e0.version + 1
    assert e1.model == e0.model
    assert (e1.path, e1.mtime, e1.sig) == (e0.path, e0.mtime, e0.sig)
    # a later DISK rewrite still hot-reloads over the promotion
    with open(path, "w") as fp:
        kernel_mod.dump("t", _kernel(seed=3), fp)
    os.utime(path, (e0.mtime + 10, e0.mtime + 10))
    assert reg.maybe_reload("k") is True
    assert reg.get("k").version == e1.version + 1
    with pytest.raises(RegistryError):
        reg.install("nope", _kernel(seed=1))


# ====================================================== promotion gate
def test_promote_then_margin_reject(tmp_path):
    sink = tmp_path / "obs.jsonl"
    obs.configure(str(sink))
    try:
        osess = _mk_osess()
        osess.add_kernel("k", _kernel(seed=9))
        osess.feed(*_stream_block(48, seed=3))
        v0 = osess.serve.registry.get("k").version
        y0 = osess.infer("k", np.ones(8))
        summary = _tick_until_promoted(osess)
        assert summary["outcomes"]["k"] == "promoted"
        assert osess.serve.registry.get("k").version == v0 + 1
        assert osess.promoter.stats["promoted"] == 1
        assert osess.promoter.last_promote_latency_s is not None
        # the promoted weights answer differently
        assert not np.array_equal(osess.infer("k", np.ones(8)), y0)
        # a candidate identical to the resident cannot clear a strict
        # margin: deterministic margin rejection
        osess.trainer.candidate_hook = \
            lambda name, w: _weights_of(osess, name)
        summary = osess.tick()
        assert summary["outcomes"]["k"] == "margin"
        assert osess.serve.registry.get("k").version == v0 + 1
        osess.close()
    finally:
        obs.configure(None)
    recs = _read(sink)
    promo = [r for r in recs if r["ev"] == "online.promote"]
    assert len(promo) == 1 and promo[0]["kernel"] == "k"
    assert promo[0]["to_version"] == promo[0]["from_version"] + 1
    assert promo[0]["cand_loss"] < promo[0]["res_loss"]
    rej = [r for r in recs if r["ev"] == "online.reject"]
    assert rej and rej[-1]["reason"] == "margin"
    assert any(r["ev"] == "serve.install" for r in recs)
    assert any(r["ev"] == "online.round" for r in recs)


def test_nan_candidate_rejected_serving_continues(tmp_path):
    sink = tmp_path / "obs.jsonl"
    obs.configure(str(sink))
    try:
        osess = _mk_osess()
        osess.add_kernel("k", _kernel(seed=9))
        osess.feed(*_stream_block(32, seed=3))
        v0 = osess.serve.registry.get("k").version
        y0 = osess.infer("k", np.ones(8))

        def poison(name, w):
            bad = [np.asarray(x).copy() for x in w]
            bad[0][0, 0] = np.nan
            return tuple(bad)

        osess.trainer.candidate_hook = poison
        summary = osess.tick()
        assert summary["outcomes"]["k"] == "sentinel"
        # the resident version keeps serving, bitwise
        assert osess.serve.registry.get("k").version == v0
        assert np.array_equal(osess.infer("k", np.ones(8)), y0)
        osess.close()
    finally:
        obs.configure(None)
    rej = [r for r in _read(sink) if r["ev"] == "online.reject"]
    assert rej and rej[0]["reason"] == "sentinel"
    assert not any(r["ev"] == "online.promote" for r in _read(sink))


def test_no_holdout_means_eval_reject_never_blind_promotion():
    osess = _mk_osess(holdout=0)
    osess.add_kernel("k", _kernel(seed=9))
    osess.feed(*_stream_block(32, seed=3))
    summary = osess.tick()
    assert summary["outcomes"]["k"] == "eval"
    assert osess.serve.registry.get("k").version == 0
    osess.close()


def test_rollback_restores_bitwise_identical_answers():
    osess = _mk_osess()
    osess.add_kernel("k", _kernel(seed=9))
    osess.feed(*_stream_block(48, seed=3))
    X = np.linspace(-1.0, 1.0, 8)
    y_before = osess.infer("k", X)
    _tick_until_promoted(osess)
    y_promoted = osess.infer("k", X)
    assert not np.array_equal(y_before, y_promoted)
    entry = osess.rollback("k")
    assert entry is not None and entry.version == 2   # never rewinds
    assert np.array_equal(osess.infer("k", X), y_before)   # bitwise
    assert osess.rollback("k") is None       # nothing left to undo
    osess.close()


def test_watch_rolls_back_on_serve_numerics_regression(
        tmp_path, monkeypatch):
    monkeypatch.setenv("HPNN_PROBES", "1")
    sink = tmp_path / "obs.jsonl"
    obs.configure(str(sink))
    try:
        osess = _mk_osess()
        osess.add_kernel("k", _kernel(seed=9))
        osess.feed(*_stream_block(48, seed=3))
        y_before = osess.infer("k", np.ones(8))
        _tick_until_promoted(osess)
        assert osess.promoter.watching("k")
        # a post-promotion dispatch goes NaN: the next watch scan must
        # roll the promotion back
        obs.probes.note_serve("k", rows=4, nan=2)
        assert osess.promoter.check_watch() == ["k"]
        assert not osess.promoter.watching("k")
        assert np.array_equal(osess.infer("k", np.ones(8)), y_before)
        osess.close()
    finally:
        obs.configure(None)
    rb = [r for r in _read(sink) if r["ev"] == "online.rollback"]
    assert rb and rb[0]["reason"] == "numerics"
    assert rb[0]["to_version"] > rb[0]["from_version"]


def test_watch_rolls_back_on_slo_breach(monkeypatch):
    osess = _mk_osess()
    osess.add_kernel("k", _kernel(seed=9))
    osess.feed(*_stream_block(48, seed=3))
    y_before = osess.infer("k", np.ones(8))
    _tick_until_promoted(osess)
    monkeypatch.setattr(
        obs.slo, "health_doc",
        lambda: {"mode": "on", "served": 10, "verdict": "breach"})
    assert osess.promoter.check_watch() == ["k"]
    assert np.array_equal(osess.infer("k", np.ones(8)), y_before)
    assert osess.promoter.stats["rollbacks"] == 1
    osess.close()


def test_watch_disarms_after_window_fake_clock(monkeypatch):
    clock = FakeClock()
    osess = _mk_osess(clock=clock,
                      gate=online.Gate(margin=0.0, watch_s=5.0))
    osess.add_kernel("k", _kernel(seed=9))
    osess.feed(*_stream_block(48, seed=3))
    _tick_until_promoted(osess)
    assert osess.promoter.watching("k")
    clock.advance(6.0)                       # past watch_s: disarm
    assert osess.promoter.check_watch() == []
    assert not osess.promoter.watching("k")
    # a breach AFTER the window closed must not roll back
    monkeypatch.setattr(
        obs.slo, "health_doc",
        lambda: {"mode": "on", "served": 10, "verdict": "breach"})
    assert osess.promoter.check_watch() == []
    assert osess.promoter.stats["rollbacks"] == 0
    osess.close()


# ====================================================== promotion race
def test_promotion_race_answers_never_torn():
    """Clients racing promotions/rollbacks see the old answer or the
    new answer, bitwise — never a mix of versions."""
    osess = _mk_osess(eval_set=_stream_block(16, seed=8))
    osess.add_kernel("k", _kernel(seed=9))
    osess.feed(*_stream_block(48, seed=3))
    x = np.linspace(-1.0, 1.0, 8)
    y_old = osess.infer("k", x)
    _tick_until_promoted(osess)
    w_good = _weights_of(osess, "k")
    y_new = osess.infer("k", x)
    assert not np.array_equal(y_old, y_new)
    # pin the candidate: every promotion from here installs exactly
    # w_good, so the only legal answers are y_old and y_new
    osess.trainer.candidate_hook = lambda name, w: w_good
    stop = threading.Event()
    churn_err = []

    def churn():
        try:
            while not stop.is_set():
                osess.rollback("k")          # resident -> w_init
                osess.tick()                 # resident -> w_good
        except Exception as exc:             # pragma: no cover
            churn_err.append(exc)

    t = threading.Thread(target=churn, daemon=True)
    t.start()
    try:
        for _ in range(120):
            y = osess.infer("k", x)
            assert np.array_equal(y, y_old) or np.array_equal(y, y_new)
    finally:
        stop.set()
        t.join(timeout=10)
    assert not churn_err
    assert osess.promoter.stats["promoted"] >= 2   # races happened
    osess.close()


# ============================================ fleet-wise group training
def test_same_topology_kernels_train_as_one_fleet_group(tmp_path):
    sink = tmp_path / "obs.jsonl"
    obs.configure(str(sink))
    try:
        osess = _mk_osess()
        osess.add_kernel("a", _kernel(seed=9))
        osess.add_kernel("b", _kernel(seed=11))          # same topology
        osess.add_kernel("c", _kernel(seed=13, hidden=(4,)))  # not
        osess.feed(*_stream_block(48, seed=3))
        summary = osess.tick()
        assert set(summary["outcomes"]) == {"a", "b", "c"}
        osess.close()
    finally:
        obs.configure(None)
    recs = _read(sink)
    rounds = [r for r in recs if r["ev"] == "online.round"]
    assert rounds and rounds[0]["members"] == 3
    assert rounds[0]["groups"] == 2          # {a, b} stacked, {c} solo
    losses = {r["kernel"] for r in recs
              if r["ev"] == "online.train_loss"}
    assert losses == {"a", "b", "c"}


def test_starved_round_and_background_thread():
    osess = _mk_osess(interval_s=0.01)
    osess.add_kernel("k", _kernel(seed=9))
    osess.feed(*_stream_block(8, seed=3))    # fewer than rows=16
    summary = osess.tick()
    assert summary.get("starved") is True
    assert osess.trainer.stats["starved"] == 1
    osess.feed(*_stream_block(48, seed=4))
    osess.start()
    assert osess.trainer.running()
    deadline = time.monotonic() + 10.0
    while (osess.trainer.stats["rounds"] < 1
           and time.monotonic() < deadline):
        time.sleep(0.01)
    assert osess.trainer.stats["rounds"] >= 1
    osess.close()
    assert not osess.trainer.running()
    doc = osess.health_doc()
    assert doc["buffer"]["depth"] > 0
    assert doc["kernels"]["k"]["version"] >= 0
    assert "promoted" in doc["promoter"]


def test_trainer_validates_batch_divides_rows():
    with pytest.raises(ValueError):
        _mk_osess(rows=16, batch=5)


# ==================================================== HTTP POST /ingest
def _post(port, path, body, timeout=10.0):
    conn = http.client.HTTPConnection("127.0.0.1", port,
                                      timeout=timeout)
    try:
        conn.request("POST", path, body=json.dumps(body).encode(),
                     headers={"Content-Type": "application/json"})
        resp = conn.getresponse()
        return resp.status, json.loads(resp.read().decode())
    finally:
        conn.close()


def test_http_ingest_requires_online_session():
    sess = serve.Session(max_batch=8, n_buckets=2, max_wait_ms=1.0)
    sess.register_kernel("k", _kernel())
    server = make_server(sess, port=0)
    port = server.server_address[1]
    threading.Thread(target=server.serve_forever, daemon=True).start()
    try:
        code, body = _post(port, "/ingest",
                           {"inputs": [0.0] * 8, "targets": [0.0, 0.0]})
        assert code == 404 and "not enabled" in body["error"]
    finally:
        server.shutdown()
        server.server_close()
        sess.close()


def test_http_ingest_feeds_buffer_and_validates():
    osess = _mk_osess()
    osess.add_kernel("k", _kernel(seed=9))
    server = make_server(osess.serve, port=0)
    port = server.server_address[1]
    threading.Thread(target=server.serve_forever, daemon=True).start()
    try:
        code, body = _post(port, "/ingest",
                           {"inputs": [0.1] * 8, "targets": [1.0, -1.0]})
        assert code == 200
        assert body["accepted"] == 1 and body["depth"] == 1
        assert body["req_id"]       # edge-minted X-Request-Id echo
        X, T = _stream_block(4, seed=1)
        code, body = _post(port, "/v1/ingest",
                           {"kernel": "k", "inputs": X.tolist(),
                            "targets": T.tolist()})
        assert code == 200 and body["accepted"] == 4
        assert osess.buffer.total_fed() == 5
        code, body = _post(port, "/ingest",
                           {"kernel": "nope", "inputs": [0.1] * 8,
                            "targets": [0.0, 0.0]})
        assert code == 404 and "nope" in body["error"]
        code, _ = _post(port, "/ingest",
                        {"inputs": "junk", "targets": [0.0, 0.0]})
        assert code == 400
        code, _ = _post(port, "/ingest",
                        {"inputs": [0.1] * 5, "targets": [0.0, 0.0]})
        assert code == 400                   # width mismatch
        code, _ = _post(port, "/ingest",
                        {"kernel": 7, "inputs": [0.1] * 8,
                         "targets": [0.0, 0.0]})
        assert code == 400
        # /healthz grew the online section
        conn = http.client.HTTPConnection("127.0.0.1", port, timeout=10)
        conn.request("GET", "/healthz")
        doc = json.loads(conn.getresponse().read().decode())
        conn.close()
        assert doc["online"]["buffer"]["depth"] >= 4
        assert "k" in doc["online"]["kernels"]
    finally:
        server.shutdown()
        server.server_close()
        osess.close()


def test_loadgen_mix_interleaves_ingest_with_infer():
    loadgen = _load_tool("loadgen")
    osess = _mk_osess()
    osess.add_kernel("k", _kernel(seed=9))
    server = make_server(osess.serve, port=0)
    port = server.server_address[1]
    threading.Thread(target=server.serve_forever, daemon=True).start()
    try:
        res = loadgen.run_closed_loop(
            f"http://127.0.0.1:{port}", kernels=("k",),
            rows_choices=(1, 2), n_in=8, n_out=2, n_clients=2,
            duration_s=0.4, ingest_frac=0.5, seed=3, timeout_s=5.0,
            max_retries=0)
        assert res["ops"].get("ingest", 0) > 0
        assert res["ops"].get("infer", 0) > 0
        assert osess.buffer.total_fed() > 0
    finally:
        server.shutdown()
        server.server_close()
        osess.close()


# ============================================================= streams
def test_streams_shapes_and_determinism():
    X1, T1 = streams.take(streams.mnist_stream(seed=4), 3)
    X2, T2 = streams.take(streams.mnist_stream(seed=4), 3)
    assert X1.shape == (3, 784) and T1.shape == (3, 10)
    assert np.array_equal(X1, X2) and np.array_equal(T1, T2)
    assert X1.min() >= 0.0 and X1.max() <= 1.0
    assert np.array_equal(T1.sum(axis=1), np.ones(3))   # one-hot
    Xx, Tx = streams.take(streams.xrd_stream(seed=4), 2)
    assert Xx.shape == (2, 128) and Tx.shape == (2, 8)
    assert Xx.max() <= 1.0 + 1e-12
    assert np.array_equal(Tx.sum(axis=1), np.ones(2))


def test_online_nn_build_from_conf_prefeeds_stream():
    from hpnn_tpu.cli import online_nn
    from hpnn_tpu.config import NNConf, NNTrain, NNType

    conf = NNConf(name="demo", type=NNType.ANN, seed=1,
                  kernel=_kernel(seed=2, n_in=784, hidden=(4,),
                                 n_out=10),
                  train=NNTrain.BP, samples=None, tests=None)
    osess, server = online_nn.build_from_conf(conf, port=0,
                                              stream="mnist",
                                              stream_n=8)
    try:
        assert osess.kernels() == ["demo"]
        assert osess.buffer.total_fed() == 8
    finally:
        server.server_close()
        osess.close()
    # width mismatch between stream and kernel is a startup error
    bad = NNConf(name="bad", type=NNType.ANN, seed=1,
                 kernel=_kernel(seed=2), train=NNTrain.BP,
                 samples=None, tests=None)
    with pytest.raises(ValueError):
        online_nn.build_from_conf(bad, port=0, stream="mnist",
                                  stream_n=4)


# ==================================================== lint_online tool
def _good_online_records():
    return [
        {"ts": 1.0, "ev": "online.ingest", "kind": "count", "n": 4,
         "total": 4},
        {"ts": 1.0, "ev": "online.buffer_depth", "kind": "gauge",
         "value": 4.0},
        {"ts": 1.1, "ev": "online.staleness_s", "kind": "gauge",
         "value": 0.5},
        {"ts": 1.2, "ev": "online.train_loss", "kind": "gauge",
         "value": 0.3, "kernel": "k"},
        {"ts": 1.2, "ev": "online.candidate_loss", "kind": "gauge",
         "value": 0.2, "kernel": "k"},
        {"ts": 1.2, "ev": "online.resident_loss", "kind": "gauge",
         "value": 0.4, "kernel": "k"},
        {"ts": 1.3, "ev": "serve.install", "kind": "count", "n": 1,
         "total": 1, "kernel": "k", "version": 1},
        {"ts": 1.3, "ev": "online.promote", "kind": "event",
         "kernel": "k", "from_version": 0, "to_version": 1,
         "cand_loss": 0.2, "res_loss": 0.4, "install_s": 0.001},
        {"ts": 1.3, "ev": "online.promote_latency_ms", "kind": "gauge",
         "value": 1.0, "kernel": "k"},
        {"ts": 1.4, "ev": "online.reject", "kind": "event",
         "kernel": "k", "reason": "margin", "step": 1},
        {"ts": 1.5, "ev": "online.rollback", "kind": "event",
         "kernel": "k", "from_version": 1, "to_version": 2,
         "restored": 0, "reason": "numerics"},
        {"ts": 1.6, "ev": "online.round", "kind": "event", "round": 0,
         "rows": 16, "members": 1, "groups": 1, "replay": 0,
         "promoted": 1, "rejected": 1, "rolled_back": 1,
         "train_s": 0.01},
    ]


def _write_sink(path, recs):
    with open(path, "w") as fp:
        for r in recs:
            fp.write(json.dumps(r) + "\n")


def test_lint_online_passes_a_clean_sink(tmp_path):
    cat = _load_tool("check_obs_catalog")
    sink = tmp_path / "ok.jsonl"
    _write_sink(sink, _good_online_records())
    assert cat.lint_online(str(sink)) == []


def test_lint_online_catches_contract_breaks(tmp_path):
    cat = _load_tool("check_obs_catalog")
    bad = _good_online_records()
    bad[7]["to_version"] = 0                 # promote must bump
    bad[9]["reason"] = "vibes"               # unknown reject reason
    bad[1]["value"] = -1.0                   # negative depth
    bad[11]["members"] = 0                   # empty round
    sink = tmp_path / "bad.jsonl"
    _write_sink(sink, bad)
    failures = "\n".join(cat.lint_online(str(sink)))
    assert "do not bump" in failures
    assert "vibes" in failures
    assert "negative" in failures
    assert "members" in failures
    # an empty sink fails: the lint demands evidence of online activity
    empty = tmp_path / "empty.jsonl"
    _write_sink(empty, [{"ts": 1.0, "ev": "serve.request",
                         "kind": "timer", "dt": 0.1}])
    assert any("no online.*" in f for f in cat.lint_online(str(empty)))
    assert "docs/online.md" in cat.DOC_PAGES


def test_lint_online_via_main_flag(tmp_path, capsys):
    cat = _load_tool("check_obs_catalog")
    sink = tmp_path / "ok.jsonl"
    _write_sink(sink, _good_online_records())
    assert cat.main(["--online", str(sink)]) == 0
    bad = tmp_path / "bad.jsonl"
    _write_sink(bad, [])
    assert cat.main(["--online", str(bad)]) == 1
    assert cat.main(["--online"]) == 2


# ======================================================= E2E acceptance
def test_e2e_mnist_stream_promotes_under_live_traffic(
        tmp_path, monkeypatch):
    """The ISSUE acceptance demo: an OnlineSession serving an
    MNIST-stream kernel ingests under live loadgen traffic, promotes a
    sentinel-clean candidate (version bump + ``online.promote``),
    improves on held-out eval, and rejects an injected-NaN candidate
    with ``online.reject`` while serving continues — and the recorded
    sink lints clean under ``check_obs_catalog --online``."""
    monkeypatch.setenv("HPNN_SPANS", "1")
    loadgen = _load_tool("loadgen")
    sink = tmp_path / "obs.jsonl"
    obs.configure(str(sink))
    osess = None
    server = None
    try:
        # held-out eval: a stream block the trainer never feeds
        Xe, Te = streams.take(streams.mnist_stream(seed=99), 48)
        osess = online.OnlineSession(
            serve_kwargs=dict(max_batch=16, n_buckets=3,
                              max_wait_ms=1.0),
            rows=32, batch=8, epochs=8, interval_s=60.0, holdout=8,
            gate=online.Gate(margin=0.0, watch_s=30.0), seed=21,
            eval_set=(Xe, Te))
        k = _kernel(seed=21, n_in=784, hidden=(16,), n_out=10)
        w_init = tuple(np.asarray(w) for w in k.weights)
        osess.add_kernel("mnist", k)
        stream = streams.mnist_stream(seed=5)
        osess.feed(*streams.take(stream, 96))
        server = make_server(osess.serve, port=0)
        port = server.server_address[1]
        threading.Thread(target=server.serve_forever,
                         daemon=True).start()
        url = f"http://127.0.0.1:{port}"

        # live mixed loadgen traffic (infer + POST /ingest) in the
        # background while the trainer rounds run in the foreground
        traffic = {}

        def drive():
            traffic["res"] = loadgen.run_closed_loop(
                url, kernels=("mnist",), rows_choices=(1, 2),
                n_in=784, n_out=10, n_clients=2, duration_s=2.5,
                ingest_frac=0.3, seed=6, timeout_s=10.0,
                max_retries=1)

        t = threading.Thread(target=drive, daemon=True)
        t.start()
        fed_mark = osess.buffer.total_fed()
        promoted = 0
        for _ in range(6):
            # keep the newest window dominated by real MNIST samples
            # (loadgen's ingest bodies are random-target noise)
            osess.feed(*streams.take(stream, 48))
            summary = osess.tick()
            promoted += summary["promoted"]
            if promoted:
                break
        t.join(timeout=30)
        assert "res" in traffic, "loadgen thread did not finish"
        res = traffic["res"]
        assert res["ops"].get("infer", 0) > 0
        assert res["ops"].get("ingest", 0) > 0      # ingested under load
        assert osess.buffer.total_fed() > fed_mark
        assert res["ok"] > 0

        # >=1 sentinel-clean promotion: version bumped, answers moved
        assert promoted >= 1
        entry = osess.serve.registry.get("mnist")
        assert entry.version >= 1
        # held-out eval improved: the resident strictly beats the
        # initial weights on data it never trained on
        loss_init = promote_mod.eval_loss(w_init, Xe, Te)
        loss_now = promote_mod.eval_loss(
            _weights_of(osess, "mnist"), Xe, Te)
        assert loss_now < loss_init

        # NaN drill: a poisoned candidate is rejected, serving
        # continues on the promoted version
        v_before = entry.version
        y_before = osess.infer("mnist", Xe[0])

        def poison(name, w):
            bad = [np.asarray(x).copy() for x in w]
            bad[0][0, 0] = np.nan
            return tuple(bad)

        osess.trainer.candidate_hook = poison
        summary = osess.tick()
        assert summary["outcomes"]["mnist"] == "sentinel"
        assert osess.serve.registry.get("mnist").version == v_before
        assert np.array_equal(osess.infer("mnist", Xe[0]), y_before)
    finally:
        if server is not None:
            server.shutdown()
            server.server_close()
        if osess is not None:
            osess.close()
        obs.configure(None)

    recs = _read(sink)
    names = {r["ev"] for r in recs}
    assert "online.promote" in names
    assert "online.reject" in names
    assert "online.ingest" in names
    assert "serve.install" in names
    spans = [r for r in recs if r["ev"] == "span.end"
             and r.get("name") == "online.train_round"]
    assert spans and all(s["members"] >= 1 for s in spans)
    # the audit trail lints clean
    cat = _load_tool("check_obs_catalog")
    assert cat.lint_online(str(sink)) == []
    assert cat.check(ROOT) == []
