"""Tenant metering plane (obs/meter.py): space-saving attribution
sketches, the cardinality governor, the fleet merge, and the blame
table (tools/tenant_report.py).

The plane's contract is the usual obs one — ``HPNN_METER`` unset ⇒
constant-time no-ops — plus its own: exported per-tenant values are
space-saving **lower bounds** whose sum conserves the exact axis
total (the ``_other`` remainder absorbs the difference); the merge
rule is commutative and associative so worker order never matters;
and *no* metric family ever carries more than K+1 distinct
``tenant=`` labels, no matter how many tenants exist."""

import importlib.util
import itertools
import json
import os
import re

import numpy as np
import pytest

from hpnn_tpu import obs, serve
from hpnn_tpu.models import kernel as kernel_mod
from hpnn_tpu.obs import export, meter, triggers
from hpnn_tpu.tenant.quota import QuotaEnforcer, QuotaExceeded, TenantSpec

ROOT = os.path.join(os.path.dirname(__file__), "..")


def _read(path):
    with open(path) as fp:
        return [json.loads(ln) for ln in fp if ln.strip()]


def _arm(monkeypatch, tmp_path, k=None):
    sink = tmp_path / "m.jsonl"
    monkeypatch.setenv("HPNN_METRICS", str(sink))
    monkeypatch.setenv("HPNN_METER", "1")
    if k is not None:
        monkeypatch.setenv("HPNN_METER_TOPK", str(k))
    obs._reset_for_tests()
    return sink


def _load_tool(name):
    spec = importlib.util.spec_from_file_location(
        name, os.path.join(ROOT, "tools", name + ".py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


class FakeClock:
    def __init__(self):
        self.t = 1000.0

    def __call__(self):
        return self.t

    def advance(self, s):
        self.t += s


# ------------------------------------------------------------ unarmed
def test_unarmed_everything_noops(monkeypatch, tmp_path):
    sink = tmp_path / "m.jsonl"
    monkeypatch.setenv("HPNN_METRICS", str(sink))
    monkeypatch.delenv("HPNN_METER", raising=False)
    obs._reset_for_tests()
    assert not meter.enabled()
    meter.note_dispatch("t:k", 0.5)
    meter.note_queue("t:k", 0.1)
    meter.note_request("t", 8)
    meter.note_shed("t")
    meter.emit_sketch()
    assert meter.export_doc() is None
    assert meter.sketch_doc() is None
    assert meter.meterz_doc() is None
    assert meter.health_doc() == {"armed": False}
    assert export.render_meter_lines(meter.export_doc()) == []
    obs.flush()
    if os.path.exists(sink):
        assert not [r for r in _read(sink)
                    if r.get("ev") == "meter.sketch"]


def test_unarmed_governor_still_bounds_gauge_labels(monkeypatch,
                                                    tmp_path):
    """The PR-17 cardinality fix must not depend on the knob: unarmed,
    a first-K-distinct admission set keeps per-tenant gauge labels
    O(K) — and the admitted set is stable on re-query."""
    monkeypatch.setenv("HPNN_METRICS", str(tmp_path / "m.jsonl"))
    monkeypatch.delenv("HPNN_METER", raising=False)
    obs._reset_for_tests()
    labels = [meter.tenant_label(f"t{i:03d}") for i in range(100)]
    named = [l for l in labels if l != meter.OTHER]
    assert len(named) == meter.DEFAULT_TOPK
    assert labels[:meter.DEFAULT_TOPK] == \
        [f"t{i:03d}" for i in range(meter.DEFAULT_TOPK)]
    assert set(labels[meter.DEFAULT_TOPK:]) == {meter.OTHER}
    # admitted names stay admitted; the tail stays _other
    assert meter.tenant_label("t000") == "t000"
    assert meter.tenant_label("t099") == meter.OTHER


# ------------------------------------------------------------- sketch
def test_space_saving_eviction_lower_bound_and_conservation():
    """The Metwally invariants directly: an evicted entry's count is
    inherited as the newcomer's err, export values are ``count - err``
    lower bounds, and every export sums to the exact total."""
    sk = meter._SpaceSaving(2)
    sk.add("a", 5.0)
    sk.add("b", 3.0)
    sk.add("c", 1.0)              # evicts b (min count); c inherits 3
    assert sk.total == 9.0
    assert sk.entries["c"] == [4.0, 3.0]
    exp = sk.export(2)
    assert exp["a"] == 5.0
    assert exp["c"] == 1.0        # lower bound, not the inflated count
    assert exp[meter.OTHER] == pytest.approx(3.0)
    assert sum(exp.values()) == pytest.approx(sk.total)


def _mk(weights, cap=1024):
    sk = meter._SpaceSaving(cap)
    for t, w in weights:
        sk.add(t, w)
    return sk


def test_merge_commutative_and_associative():
    a = _mk([("x", 5.0), ("y", 2.0), ("z", 1.0)])
    b = _mk([("y", 7.0), ("w", 3.0)])
    c = _mk([("x", 1.0), ("w", 1.0), ("q", 4.0)])
    ab = a.merge(b).to_doc()
    ba = b.merge(a).to_doc()
    assert ab == ba
    left = a.merge(b).merge(c).to_doc()
    right = a.merge(b.merge(c)).to_doc()
    assert left == right
    assert left["total"] == pytest.approx(24.0)
    assert left["entries"]["y"] == [9.0, 0.0]


def test_merge_sketch_docs_order_independent():
    docs = []
    for i, weights in enumerate(([("x", 5.0), ("y", 2.0)],
                                 [("y", 7.0), ("w", 3.0)],
                                 [("x", 1.0), ("q", 4.0)])):
        docs.append({"k": 4, "tenants_seen": 2 + i,
                     "axes": {"device_s": _mk(weights).to_doc(),
                              "rows": _mk([("x", float(i + 1))]).to_doc()}})
    views = [meter.merge_sketch_docs(list(p))
             for p in itertools.permutations(docs)]
    assert all(v == views[0] for v in views[1:])
    dev = views[0]["axes"]["device_s"]
    assert dev["total"] == pytest.approx(22.0)
    assert dev["top"]["y"] == pytest.approx(9.0)
    assert views[0]["tenants_seen"] == 4      # max across workers


def test_merged_topk_superset_of_true_topk_zipf():
    """Four workers each sketch a slice of a zipf-headed population
    with truncating caps (evictions do happen); the fleet merge's
    top-K must still contain every true top-K tenant."""
    k, n_head, n_tail = 8, 8, 192
    head = [(f"h{i}", 100.0 / (i + 1)) for i in range(n_head)]
    tail = [(f"t{i:03d}", 1.0) for i in range(n_tail)]
    rng = np.random.RandomState(0)
    docs = []
    for w in range(4):
        weights = [(t, v / 4.0) for t, v in head + tail]
        rng.shuffle(weights)      # per-worker arrival order differs
        docs.append({"k": k, "axes":
                     {"device_s": _mk(weights, cap=64).to_doc()}})
    merged = meter.merge_sketch_docs(docs)
    named = set(merged["axes"]["device_s"]["top"]) - {meter.OTHER}
    assert {t for t, _ in head} <= named
    total = merged["axes"]["device_s"]["total"]
    assert total == pytest.approx(sum(v for _, v in head + tail))
    assert sum(merged["axes"]["device_s"]["top"].values()) == \
        pytest.approx(total)      # conservation survives the merge


def test_other_conservation_through_the_armed_module(monkeypatch,
                                                     tmp_path):
    _arm(monkeypatch, tmp_path, k=4)
    fed = 0.0
    for i in range(50):
        w = float(50 - i)
        meter.note_request(f"t{i:02d}", int(w))
        fed += w
    doc = meter.export_doc()
    rows = doc["rows"]
    assert len(rows) <= 4 + 1 and meter.OTHER in rows
    assert sum(rows.values()) == pytest.approx(fed)
    census = meter.meterz_doc()
    assert census["axes"]["rows"]["total"] == pytest.approx(fed)


# ----------------------------------------------------------- governor
def test_export_cardinality_at_10k_tenants(monkeypatch, tmp_path):
    """The acceptance bound: 10k distinct tenants, and every exported
    metric family still carries at most K+1 ``tenant=`` labels."""
    _arm(monkeypatch, tmp_path, k=32)
    for i in range(10_000):
        meter.note_dispatch(f"t{i:05d}:k", 1e-4 * (1 + i % 7))
        meter.note_request(f"t{i:05d}", 4)
    lines = export.render_meter_lines(meter.export_doc())
    per_family = {}
    for ln in lines:
        m = re.match(r'(hpnn_meter_\w+_total)\{tenant="([^"]+)"\}', ln)
        if m:
            per_family.setdefault(m.group(1), set()).add(m.group(2))
    assert set(per_family) == {"hpnn_meter_device_seconds_total",
                               "hpnn_meter_rows_total"}
    for fam, tenants in per_family.items():
        assert len(tenants) <= 33, fam
        assert meter.OTHER in tenants, fam
    # conservation holds in the same regime
    doc = meter.export_doc()
    assert sum(doc["rows"].values()) == pytest.approx(40_000.0)


def test_tenant_label_routes_topk_when_armed(monkeypatch, tmp_path):
    _arm(monkeypatch, tmp_path, k=2)
    meter.note_dispatch("big:k", 10.0)
    meter.note_dispatch("med:k", 5.0)
    meter.note_dispatch("small:k", 0.1)
    assert meter.tenant_label("big") == "big"
    assert meter.tenant_label("med") == "med"
    assert meter.tenant_label("small") == meter.OTHER
    assert meter.tenant_label("never-seen") == meter.OTHER


def test_quota_gauges_carry_governed_labels(monkeypatch, tmp_path):
    """The quota layer's per-tenant gauges (the PR-17 cardinality
    bomb) route labels through the governor; the shed *count* events
    keep the real tenant name for the alert→capsule path."""
    sink = _arm(monkeypatch, tmp_path, k=2)
    meter.note_dispatch("big:k", 10.0)
    meter.note_dispatch("med:k", 5.0)
    for t in ("big", "med"):      # heavier shedders than the tail, so
        for _ in range(3):        # "tail" is outside EVERY axis's top-K
            meter.note_shed(t)
    clk = FakeClock()
    q = QuotaEnforcer({"tail": TenantSpec("tail", "gold", rate_rps=1.0,
                                          burst_s=1.0)}, clock=clk)
    q.admit("big")
    q.admit("tail")               # burns the one token
    with pytest.raises(QuotaExceeded):
        q.admit("tail")
    obs.flush()
    recs = _read(sink)
    inflight = [r for r in recs if r.get("ev") == "tenant.inflight"]
    assert inflight and inflight[0]["tenant"] == "big"
    rates = [r for r in recs if r.get("ev") == "tenant.shed_rate"]
    assert rates and rates[-1]["tenant"] == meter.OTHER
    sheds = [r for r in recs if r.get("ev") == "tenant.shed"]
    assert sheds and sheds[-1]["tenant"] == "tail"   # real name kept
    # ...and the shed tap billed the real tenant on the sheds axis
    assert meter.sketch_doc()["axes"]["sheds"]["entries"]["tail"] == \
        [1.0, 0.0]


# ------------------------------------------------------- serving path
def test_serve_dispatch_and_queue_feed_the_sketches(monkeypatch,
                                                    tmp_path):
    """The real serve path attributes device and queue seconds to the
    owner tenant (the ``tenant:`` prefix), and the throttled
    ``meter.sketch`` record lands in the sink on flush."""
    sink = _arm(monkeypatch, tmp_path)
    kern, _ = kernel_mod.generate(17, 8, [5], 2)
    sess = serve.Session(max_batch=8, n_buckets=1, max_wait_ms=0.5)
    try:
        sess.register_kernel("acme:srv", kern)
        rng = np.random.RandomState(5)
        for _ in range(8):
            sess.infer("acme:srv", rng.normal(size=8))
    finally:
        sess.close()
    doc = meter.export_doc()
    assert doc["device_s"].get("acme", 0.0) > 0.0
    assert doc["queue_s"].get("acme", 0.0) >= 0.0
    meter.emit_sketch()
    obs.flush()
    recs = [r for r in _read(sink) if r.get("ev") == "meter.sketch"]
    assert recs
    last = recs[-1]
    assert last["k"] == meter.DEFAULT_TOPK
    assert "acme" in last["axes"]["device_s"]["entries"]
    assert "acme" in last["export"]["device_s"]


def test_capture_capsule_carries_meter_json(monkeypatch, tmp_path):
    _arm(monkeypatch, tmp_path)
    monkeypatch.setenv("HPNN_CAPSULE_DIR", str(tmp_path / "caps"))
    monkeypatch.setenv("HPNN_CAPSULE_PROFILE_MS", "0")
    obs._reset_for_tests()
    meter.note_dispatch("acme:k", 0.25)
    man = triggers.capture("manual")
    assert man is not None and "meter.json" in man["files"]
    doc = json.load(open(os.path.join(man["capsule"], "meter.json")))
    assert doc["axes"]["device_s"]["entries"]["acme"][0] == \
        pytest.approx(0.25)
    assert doc["export"]["device_s"]["acme"] == pytest.approx(0.25)


def test_capture_without_meter_has_no_artifact(monkeypatch, tmp_path):
    monkeypatch.setenv("HPNN_METRICS", str(tmp_path / "m.jsonl"))
    monkeypatch.delenv("HPNN_METER", raising=False)
    monkeypatch.setenv("HPNN_CAPSULE_DIR", str(tmp_path / "caps"))
    monkeypatch.setenv("HPNN_CAPSULE_PROFILE_MS", "0")
    obs._reset_for_tests()
    man = triggers.capture("manual")
    assert man is not None and "meter.json" not in man["files"]


# --------------------------------------------------------- blame table
def test_tenant_report_merge_matches_meter_merge():
    """tools/tenant_report.py re-implements the fleet merge stdlib-only
    (its docstring promises this test); on non-truncating inputs the
    two implementations must agree exactly."""
    tenant_report = _load_tool("tenant_report")
    docs = []
    for weights in ([("x", 5.0), ("y", 2.0)],
                    [("y", 7.0), ("w", 3.0)],
                    [("x", 1.0), ("q", 4.0)]):
        docs.append({"k": 8, "tenants_seen": 4,
                     "axes": {"device_s": _mk(weights).to_doc()}})
    ours = meter.merge_sketch_docs(docs, k=8)
    theirs = tenant_report.merge_docs(docs)
    dev = theirs["axes"]["device_s"]
    assert dev["total"] == pytest.approx(
        ours["axes"]["device_s"]["total"])
    # exact inputs (err=0, no truncation): lower bounds == counts,
    # so the governed top view equals the merged entries verbatim
    assert ours["axes"]["device_s"]["top"] == \
        {t: round(c - e, 9) for t, (c, e) in dev["entries"].items()}
    assert theirs["k"] == ours["k"] == 8


def test_tenant_report_blames_the_hog_within_5pct(monkeypatch,
                                                  tmp_path):
    """End-to-end through the sink: known attribution (hog burns 60%
    of device seconds), two cumulative emissions (the loader must keep
    the latest, not sum a worker against itself), then the blame table
    names the hog with its share within the 5% acceptance bar."""
    sink = _arm(monkeypatch, tmp_path)
    tenant_report = _load_tool("tenant_report")
    for _ in range(10):
        meter.note_dispatch("hog:k", 0.3)
        meter.note_dispatch("v-00:k", 0.15)
        meter.note_dispatch("v-01:k", 0.05)
    meter.emit_sketch()               # mid-run cumulative record
    meter.note_shed("hog")
    meter.emit_sketch()               # final cumulative record
    obs.flush()
    docs = tenant_report.load_meter_docs([str(sink)])
    assert len(docs) == 1             # latest-wins, one worker
    rep = tenant_report.analyze(docs, top=3)
    assert rep["ranked_by"] == "device_s"
    top = rep["tenants"][0]
    assert top["tenant"] == "hog"
    assert top["share_pct"] == pytest.approx(60.0, abs=5.0)
    assert top["sheds"] == pytest.approx(1.0)
    assert rep["totals"]["device_s"] == pytest.approx(5.0)  # not 10:
    # a summed-cumulative bug would double the fleet total
    text = tenant_report.render(rep)
    assert "hog" in text and "_other" in text


# ---------------------------------------------------------------- lint
def test_lint_meter_passes_a_real_sink_and_bites_on_bad(monkeypatch,
                                                        tmp_path):
    cat = _load_tool("check_obs_catalog")
    sink = _arm(monkeypatch, tmp_path, k=2)
    for i in range(8):
        meter.note_dispatch(f"t{i}:k", 0.01 * (i + 1))
    meter.emit_sketch()
    obs.flush()
    assert cat.lint_meter(str(sink)) == []
    # quiet sink: armed lint run with no meter records must fail
    quiet = tmp_path / "quiet.jsonl"
    quiet.write_text('{"ev": "serve.request"}\n')
    assert cat.lint_meter(str(quiet))
    # crafted violations: err > count, > k named exports, and a
    # truncated sketch whose export lost the _other rollup
    bad = tmp_path / "bad.jsonl"
    bad.write_text(json.dumps({
        "ev": "meter.sketch", "k": 1, "tenants_seen": 3,
        "axes": {"device_s": {"total": 6.0,
                              "entries": {"a": [1.0, 2.0],
                                          "b": [2.0, 0.0],
                                          "c": [3.0, 0.0]}}},
        "export": {"device_s": {"a": 1.0, "b": 2.0, "c": 3.0}},
    }) + "\n")
    failures = cat.lint_meter(str(bad))
    assert len(failures) >= 3
