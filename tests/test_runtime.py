"""Runtime layer: the JAX_PLATFORMS re-assert path (public API only —
VERDICT r3 asked for the ``jax._src`` probe to go)."""

import sys

from hpnn_tpu import runtime
from hpnn_tpu.utils import logging as log


def test_honor_platform_env_noop_when_unset(monkeypatch, capsys):
    monkeypatch.delenv("JAX_PLATFORMS", raising=False)
    assert runtime._honor_platform_env() is None
    assert capsys.readouterr().err == ""


def test_honor_platform_env_applies_without_initializing(monkeypatch):
    """The config re-assert must NOT create backends — init_all calls
    it before jax.distributed.initialize, which requires no backend to
    exist yet.  (Backends are already live in this suite, so the real
    property is pinned by the 2-process CLI test, which would fail
    with '#tasks=1' if this ever initialized early; here we check the
    return value contract.)"""
    monkeypatch.setenv("JAX_PLATFORMS", "cpu")
    assert runtime._honor_platform_env() == "cpu"


def test_warn_platform_mismatch_silent_when_matching(capsys):
    log.set_verbose(2)
    try:
        runtime._warn_platform_mismatch("cpu")
    finally:
        log.set_verbose(0)
    assert "JAX_PLATFORMS" not in capsys.readouterr().err


def test_warn_platform_mismatch_warns_when_ignored(capsys):
    """Backends are already initialized on cpu in this suite; asking
    for an accelerator can no longer take effect and must WARN
    (the silent-degradation case the old jax._src probe existed for)."""
    import jax

    log.set_verbose(2)
    try:
        runtime._warn_platform_mismatch("tpu")
    finally:
        log.set_verbose(0)
    err = capsys.readouterr().err
    assert "JAX_PLATFORMS=tpu" in err
    assert jax.default_backend() == "cpu"


def test_warn_platform_mismatch_accelerator_alias_silent(capsys,
                                                         monkeypatch):
    """An accelerator plugin answering under its canonical name
    (JAX_PLATFORMS=axon honored, backend reports 'tpu') must NOT warn
    — only cpu↔accelerator mismatches are real defeats."""
    import jax

    monkeypatch.setattr(jax, "default_backend", lambda: "tpu")
    log.set_verbose(2)
    try:
        runtime._warn_platform_mismatch("axon")
    finally:
        log.set_verbose(0)
    assert "JAX_PLATFORMS" not in capsys.readouterr().err


def test_warn_platform_mismatch_fallback_list_silent(capsys, monkeypatch):
    """A priority list with a cpu fallback ("axon,cpu") honored by the
    accelerator (reported under its canonical name) must not warn."""
    import jax

    monkeypatch.setattr(jax, "default_backend", lambda: "tpu")
    log.set_verbose(2)
    try:
        runtime._warn_platform_mismatch("axon,cpu")
    finally:
        log.set_verbose(0)
    assert "JAX_PLATFORMS" not in capsys.readouterr().err
