"""Multi-replica serving scale-out (hpnn_tpu/serve/router.py,
docs/serving.md "Scale-out").

Acceptance bar (ISSUE): a Router over N replicas answers every
registry kernel **bitwise-identically** to a single-replica Session;
a promotion fanned out mid-traffic is seen by every request as
bitwise old-version or new-version, never a torn mix; unready /
killed / shedding replicas are routed around without losing requests;
oversized row blocks spill to the TP path; a replica booting against
a warm ``HPNN_COMPILE_CACHE_DIR`` records persistent-cache hits in
the ``/healthz`` document; and the whole obs surface passes the
``tools/check_obs_catalog.py --serve-replicas`` schema lint.
"""

import importlib.util
import json
import os
import threading
import time

import numpy as np
import pytest

from hpnn_tpu import serve
from hpnn_tpu.models import ann, kernel as kernel_mod, snn
from hpnn_tpu.serve.batcher import Shed
from hpnn_tpu.serve.router import Router

ROOT = os.path.join(os.path.dirname(__file__), "..")


def _kernel(seed=7, n_in=8, hiddens=(5,), n_out=2):
    k, _ = kernel_mod.generate(seed, n_in, list(hiddens), n_out)
    return k


def _read_sink(path):
    with open(path) as fp:
        return [json.loads(ln) for ln in fp if ln.strip()]


# --------------------------------------------------------------- parity
def test_n_replica_parity_every_registry_kernel():
    """The scale-out contract: for EVERY registry kernel (ann + snn),
    a 3-replica Router answers bitwise-identically to a
    single-replica Session across single vectors and row blocks."""
    router = Router(3, max_batch=16, max_wait_ms=0.5)
    single = serve.Session(max_batch=16, max_wait_ms=0.5)
    try:
        specs = [("a", _kernel(seed=7), "ann"),
                 ("s", _kernel(seed=20), "snn")]
        for name, k, model in specs:
            router.register_kernel(name, k, model=model)
            single.register_kernel(name, k, model=model)
        rng = np.random.RandomState(3)
        for name, _k, _model in specs:
            vec = rng.uniform(-1, 1, 8)
            assert np.array_equal(router.infer(name, vec),
                                  single.infer(name, vec))
            for rows in (1, 3, 8, 21):
                X = rng.uniform(-1, 1, (rows, 8))
                assert np.array_equal(router.infer(name, X),
                                      single.infer(name, X))
    finally:
        router.close()
        single.close()


def test_router_is_session_shaped():
    """The Session surface callers rely on: kernels(), health() doc
    shape, ready_doc(), registry/engine facades."""
    router = Router(2, max_batch=8, max_wait_ms=0.5)
    try:
        router.register_kernel("k", _kernel())
        assert router.kernels() == ["k"]
        assert router.registry.get("k").version == 0
        assert router.engine.buckets == \
            router.replicas[0].engine.buckets
        assert router.is_ready()
        doc = router.health()
        assert doc["ready"] is True
        assert doc["router"]["n_replicas"] == 2
        assert doc["router"]["live_replicas"] == 2
        assert set(doc["replicas"]) == {"r0", "r1"}
        for rdoc in doc["replicas"].values():
            assert rdoc["ready"] is True
            assert rdoc["outstanding"] == 0
        # batchers are replica-prefixed the way training sinks are
        assert all(name.startswith(("r0/", "r1/"))
                   for name in doc["batchers"])
    finally:
        router.close()


# ---------------------------------------------------------------- fence
def test_promotion_fence_old_or_new_never_torn():
    """Install a new version while requests stream: every answer must
    be bitwise old-version or bitwise new-version output."""
    k_old, k_new = _kernel(seed=7), _kernel(seed=11)
    router = Router(3, max_batch=16, max_wait_ms=0.5)
    try:
        router.register_kernel("k", k_old)
        X = np.linspace(-1.0, 1.0, 24).reshape(3, 8)
        out_old = np.stack([np.asarray(ann.run(k_old.weights, x))
                            for x in X])
        out_new = np.stack([np.asarray(ann.run(k_new.weights, x))
                            for x in X])
        assert not np.array_equal(out_old, out_new)

        stop = threading.Event()
        torn: list = []

        def infer_loop():
            while not stop.is_set():
                out = np.asarray(router.infer("k", X))
                if not (np.array_equal(out, out_old)
                        or np.array_equal(out, out_new)):
                    torn.append(out)
                    return

        threads = [threading.Thread(target=infer_loop)
                   for _ in range(4)]
        for t in threads:
            t.start()
        for k in (k_new, k_old, k_new):  # three promotions mid-flight
            router.install_kernel("k", k)
            time.sleep(0.05)
        stop.set()
        for t in threads:
            t.join(timeout=10)
        assert torn == [], "a request saw a torn old/new weight mix"
        assert router.registry.get("k").version == 3
        # converged: every live replica agrees on the version
        assert {rep.registry.get("k").version
                for rep in router.replicas} == {3}
    finally:
        router.close()


# -------------------------------------------------------------- routing
def test_unready_replica_is_routed_around(tmp_path):
    from hpnn_tpu import obs

    sink = tmp_path / "obs.jsonl"
    obs.configure(str(sink))
    try:
        router = Router(2, max_batch=8, max_wait_ms=0.5)
        router.register_kernel("k", _kernel())
        router.replicas[0].mark_unready("draining")
        assert router.is_ready()          # one survivor keeps the edge
        for _ in range(5):
            router.infer("k", np.zeros(8))
        router.replicas[0].mark_ready()
        router.close()
    finally:
        obs.configure(None)
    routes = [r for r in _read_sink(sink) if r["ev"] == "router.route"]
    assert routes and all(r["rank"] == 1 for r in routes)


def test_kill_replica_survivors_answer_bitwise(tmp_path):
    """kill_replica takes a replica out of rotation; survivors keep
    answering bitwise and a later promotion reaches only the living
    (the dead replica's frozen registry must not poison reads)."""
    router = Router(3, max_batch=8, max_wait_ms=0.5)
    try:
        k0 = _kernel(seed=7)
        router.register_kernel("k", k0)
        probe = np.linspace(-1.0, 1.0, 8)
        before = np.asarray(router.infer("k", probe))
        router.kill_replica(0)
        doc = router.health()
        assert doc["router"]["live_replicas"] == 2
        assert doc["replicas"]["r0"]["status"] == "closed"
        assert router.is_ready()
        assert np.array_equal(router.infer("k", probe), before)
        # promotion after the kill lands on survivors only
        k1 = _kernel(seed=11)
        router.install_kernel("k", k1)
        assert router.registry.get("k").version == 1
        expect = np.asarray(ann.run(k1.weights, probe))
        assert np.array_equal(router.infer("k", probe), expect)
    finally:
        router.close()


def test_shed_reroutes_and_cools_the_replica(tmp_path):
    """A replica that sheds is routed around — the request lands on
    the next-best replica — and cools off for its retry_after_s, so
    follow-up requests skip it without even asking."""
    from hpnn_tpu import obs

    sink = tmp_path / "obs.jsonl"
    router = Router(2, max_batch=8, max_wait_ms=0.5)
    try:
        router.register_kernel("k", _kernel())
        real_infer = router.replicas[0].infer

        def shedding_infer(name, x, **kw):
            raise Shed("saturated", reason="queue_age",
                       retry_after_s=30.0)

        router.replicas[0].infer = shedding_infer
        obs.configure(str(sink))
        try:
            out = router.infer("k", np.zeros(8))   # rerouted, answered
            assert np.asarray(out).shape == (2,)
            for _ in range(3):                     # r0 cooling: skipped
                router.infer("k", np.zeros(8))
        finally:
            obs.configure(None)
        router.replicas[0].infer = real_infer
        recs = _read_sink(sink)
        sheds = [r for r in recs if r["ev"] == "router.shed_around"]
        assert len(sheds) == 1 and sheds[0]["rank"] == 0
        assert sheds[0]["reason"] == "queue_age"
        routes = [r for r in recs if r["ev"] == "router.route"]
        assert [r["rank"] for r in routes].count(0) == 1  # one attempt
        assert all(r["rank"] == 1 for r in routes[1:])
        assert router.health()["replicas"]["r0"]["cooling"] is True
    finally:
        router.close()


def test_all_replicas_refusing_raises_shed():
    router = Router(2, max_batch=8, max_wait_ms=0.5)
    try:
        router.register_kernel("k", _kernel())
        router.mark_unready("maintenance")
        assert not router.is_ready()
        with pytest.raises(Shed):
            router.infer("k", np.zeros(8))
        with pytest.raises(KeyError):
            router.infer("nope", np.zeros(8))
    finally:
        router.close()


# ------------------------------------------------------------- spin-up
def test_spawn_replica_pins_versions_and_answers():
    router = Router(2, max_batch=8, max_wait_ms=0.5)
    try:
        router.register_kernel("k", _kernel(seed=7))
        k1 = _kernel(seed=11)
        router.install_kernel("k", k1)       # every replica at v1
        rep = router.spawn_replica()
        assert rep.rank == 2
        assert rep.registry.get("k").version == 1   # pinned, not 0
        probe = np.linspace(-1.0, 1.0, 8)
        expect = np.asarray(ann.run(k1.weights, probe))
        # the spawned replica answers identically through the router
        for _ in range(6):
            assert np.array_equal(router.infer("k", probe), expect)
    finally:
        router.close()


# ------------------------------------------------------------- TP spill
def test_tp_spillover_for_oversized_row_blocks(tmp_path):
    """Row blocks exceeding the bucket menu spill to the TP batched
    forward (parallel/tp.py) instead of chunking through one
    replica's largest bucket."""
    from hpnn_tpu import obs

    sink = tmp_path / "obs.jsonl"
    router = Router(2, max_batch=8, n_buckets=1, max_wait_ms=0.5,
                    spill=True)
    try:
        k = _kernel(seed=9)
        router.register_kernel("k", k)
        X = np.random.RandomState(5).uniform(-1, 1, (24, 8))
        obs.configure(str(sink))
        try:
            out = np.asarray(router.infer("k", X))
        finally:
            obs.configure(None)
        assert out.shape == (24, 2)
        ref = np.stack([np.asarray(ann.run(k.weights, x)) for x in X])
        # TP numerics, not the parity engine's bitwise contract
        np.testing.assert_allclose(out, ref, rtol=1e-10, atol=1e-12)
        recs = _read_sink(sink)
        spills = [r for r in recs if r["ev"] == "router.spill"]
        assert spills and spills[0]["rows"] == 24
        assert any(r["ev"] == "router.spill_time" for r in recs)
        assert "k" in router.health()["router"]["spilled_kernels"]
    finally:
        router.close()


# -------------------------------------------------------- compile cache
def test_persistent_compile_cache_warm_boot(tmp_path):
    """A replica booting against a warm HPNN_COMPILE_CACHE_DIR reads
    executables off disk: warm-hit counters move and /healthz grows
    the compile_cache.persistent section."""
    from hpnn_tpu.serve import compile_cache

    cache_dir = str(tmp_path / "xla")
    os.environ[compile_cache.ENV_DIR] = cache_dir
    compile_cache._reset_for_tests()
    try:
        cold = Router(1, max_batch=8, n_buckets=1, max_wait_ms=0.5,
                      mode="compiled")
        cold.register_kernel("k", _kernel(seed=9))
        expect = np.asarray(cold.infer("k", np.zeros(8)))
        cold.close()
        assert os.path.isdir(cache_dir) and os.listdir(cache_dir)

        compile_cache._reset_for_tests()      # simulate a new process
        os.environ[compile_cache.ENV_DIR] = cache_dir
        warm = Router(1, max_batch=8, n_buckets=1, max_wait_ms=0.5,
                      mode="compiled")
        warm.register_kernel("k", _kernel(seed=9))
        hits, _misses = compile_cache.counters()
        assert hits > 0, "warm boot never hit the persistent cache"
        rate = compile_cache.hit_rate()
        assert rate is not None and rate > 0
        doc = warm.health()
        persistent = doc["compile_cache"]["persistent"]
        assert persistent["dir"] == cache_dir
        assert persistent["hits"] == hits
        assert persistent["entries"] > 0 and persistent["bytes"] > 0
        # warm executables answer bitwise like the cold ones
        assert np.array_equal(warm.infer("k", np.zeros(8)), expect)
        warm.close()
    finally:
        os.environ.pop(compile_cache.ENV_DIR, None)
        compile_cache._reset_for_tests()


def test_cache_unarmed_without_knob():
    from hpnn_tpu.serve import compile_cache

    compile_cache._reset_for_tests()
    assert compile_cache.configured_dir() is None
    assert compile_cache.arm() is False
    assert compile_cache.stats() is None
    sess = serve.Session(max_batch=8, max_wait_ms=0.5)
    try:
        sess.register_kernel("k", _kernel())
        assert "persistent" not in sess.health()["compile_cache"]
    finally:
        sess.close()


# ------------------------------------------------------------- obs lint
def test_router_sink_passes_serve_replicas_lint(tmp_path):
    """Drive the full router surface with the sink armed, then run
    tools/check_obs_catalog.py lint_serve_replicas over the records —
    the frozen-schema proof for the router.* / replica.* family."""
    from hpnn_tpu import obs

    spec = importlib.util.spec_from_file_location(
        "check_obs_catalog",
        os.path.join(ROOT, "tools", "check_obs_catalog.py"))
    lint_mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(lint_mod)

    sink = tmp_path / "obs.jsonl"
    obs.configure(str(sink))
    try:
        router = Router(3, max_batch=8, max_wait_ms=0.5)
        router.register_kernel("k", _kernel())
        rng = np.random.RandomState(1)
        for rows in (1, 4, 7):
            router.infer("k", rng.uniform(-1, 1, (rows, 8)))
        router.infer("k", np.zeros(8))
        real_infer = router.replicas[0].infer

        def _shed(*_a, **_kw):
            raise Shed("busy", reason="queue_age", retry_after_s=0.01)

        router.replicas[0].infer = _shed
        router.infer("k", np.zeros(8))        # shed_around record
        router.replicas[0].infer = real_infer
        router.install_kernel("k", _kernel(seed=11))  # fence record
        router.kill_replica(2)                # replica_down record
        router.spawn_replica()                # replica_up record
        router.infer("k", np.zeros(8))
        router.close()
    finally:
        obs.configure(None)
    failures = lint_mod.lint_serve_replicas(str(sink))
    assert failures == [], failures
    evs = {r["ev"] for r in _read_sink(sink)}
    assert {"router.route", "router.shed_around", "router.fence",
            "router.replica_down", "router.replica_up",
            "replica.outstanding"} <= evs


def test_lint_serve_replicas_bites_on_bad_records(tmp_path):
    spec = importlib.util.spec_from_file_location(
        "check_obs_catalog",
        os.path.join(ROOT, "tools", "check_obs_catalog.py"))
    lint_mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(lint_mod)

    bad = tmp_path / "bad.jsonl"
    bad.write_text(json.dumps(
        {"ev": "router.route", "kind": "count", "rank": -1,
         "kernel": "", "rows": 0}) + "\n" + json.dumps(
        {"ev": "replica.outstanding", "kind": "gauge", "rank": 0,
         "value": -3.0}) + "\n")
    failures = lint_mod.lint_serve_replicas(str(bad))
    assert len(failures) >= 4
    empty = tmp_path / "empty.jsonl"
    empty.write_text(json.dumps({"ev": "serve.request"}) + "\n")
    assert lint_mod.lint_serve_replicas(str(empty))


# -------------------------------------------------------- env + HTTP
def test_router_replica_count_from_env(monkeypatch):
    monkeypatch.setenv("HPNN_SERVE_REPLICAS", "3")
    router = Router(max_batch=8, max_wait_ms=0.5)
    try:
        assert len(router.replicas) == 3
    finally:
        router.close()
    with pytest.raises(ValueError):
        Router(0)


def test_http_front_end_over_router():
    """make_server works unchanged over a Router: infer round-trips,
    /healthz carries the router section, /readyz follows replica
    readiness."""
    import http.client

    router = Router(2, max_batch=8, max_wait_ms=0.5)
    server = serve.make_server(router)
    port = server.server_address[1]
    thread = threading.Thread(target=server.serve_forever, daemon=True)
    thread.start()
    try:
        router.register_kernel("k", _kernel())
        conn = http.client.HTTPConnection("127.0.0.1", port, timeout=5)
        body = json.dumps({"kernel": "k",
                           "inputs": [0.0] * 8}).encode()
        conn.request("POST", "/v1/infer", body=body,
                     headers={"Content-Type": "application/json"})
        resp = conn.getresponse()
        doc = json.loads(resp.read())
        assert resp.status == 200 and len(doc["outputs"]) == 2
        conn.request("GET", "/healthz")
        resp = conn.getresponse()
        hdoc = json.loads(resp.read())
        assert resp.status == 200
        assert hdoc["router"]["n_replicas"] == 2
        conn.close()
    finally:
        server.shutdown()
        server.server_close()
        router.close()
