"""Online per-phase blame attribution (obs/blame.py, ``HPNN_BLAME``)
and its shared classifier core with tools/tail_report.py.

The plane's contract: unset ⇒ one env read then constant-time no-ops;
armed ⇒ every closing request root folds the same exclusive-time split
the offline report computes into a rolling window, published as
``blame.*_pct`` gauges and served to the tune engine as
:func:`fleet_doc`.  The golden pin below holds the tail_report
refactor behavior-identical, and the agreement test holds the online
and offline splits within 1pp per phase on the same traffic."""

import importlib.util
import json
import os

import numpy as np
import pytest

from hpnn_tpu import obs, serve
from hpnn_tpu.models import kernel as kernel_mod
from hpnn_tpu.obs import blame, triggers

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _read(path):
    if not os.path.exists(path):
        return []                # sink lazily created on first record
    with open(path) as fp:
        return [json.loads(ln) for ln in fp if ln.strip()]


def _load_tool(name):
    spec = importlib.util.spec_from_file_location(
        name, os.path.join(ROOT, "tools", f"{name}.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def _arm(monkeypatch, tmp_path, **env):
    monkeypatch.setenv("HPNN_METRICS", str(tmp_path / "m.jsonl"))
    for key, val in env.items():
        monkeypatch.setenv(key, str(val))
    obs._reset_for_tests()
    return tmp_path / "m.jsonl"


# One fixed request tree, used by the split/golden/online tests alike:
# root 1.0s; queue 0.25; dispatch 0.40 with a nested 0.10 spill (so
# its exclusive time is 0.30); a failed dispatch attempt 0.05 (the
# shed marker must win over the name); gap = the uncovered 0.30.
_TREE = [
    {"span": 2, "parent": 1, "name": "serve.batch.queue",
     "t0": 0.0, "dt": 0.25},
    {"span": 4, "parent": 3, "name": "serve.spill_reload",
     "t0": 0.0, "dt": 0.10},
    {"span": 3, "parent": 1, "name": "serve.dispatch",
     "t0": 0.0, "dt": 0.40},
    {"span": 5, "parent": 1, "name": "serve.dispatch",
     "t0": 0.0, "dt": 0.05, "failed": "Shed"},
    {"span": 1, "parent": None, "name": "serve.request",
     "t0": 0.0, "dt": 1.0, "req_id": "r1", "kernel": "k"},
]

_TREE_PCT = {"queue": 25.0, "dispatch": 30.0, "spill": 10.0,
             "shed_retry": 5.0, "other": 0.0, "gap": 30.0}


def _feed(records, base=0):
    """Feed one raw tree through the online tap, refs offset so
    repeated trees never collide (children before root, as the span
    lifecycle guarantees)."""
    for rec in records:
        rec = dict(rec)
        rec["span"] += base
        if rec["parent"] is not None:
            rec["parent"] += base
        blame.note_record(rec)


# ----------------------------------------------------------- pure core
@pytest.mark.parametrize("name,failed,want", [
    ("serve.batch.queue", None, "queue"),
    ("cluster.queue.wait", None, "queue"),
    ("serve.dispatch", None, "dispatch"),
    ("serve.spill_reload", None, "spill"),
    ("serve.dispatch", "Shed", "shed_retry"),       # shed wins
    ("serve.batch.queue", "QueueFull", "shed_retry"),
    ("serve.encode", "ValueError", "other"),        # not a shed fail
    ("serve.encode", None, "other"),
    (None, None, "other"),
])
def test_phase_of_classification(name, failed, want):
    fields = {} if failed is None else {"failed": failed}
    assert blame.phase_of({"name": name, "fields": fields}) == want


def test_normalize_record_splits_structure_from_fields():
    norm = blame.normalize_record(
        {"ev": "span.end", "kind": "span", "span": 7, "parent": 3,
         "name": "serve.dispatch", "t0": 1.0, "dt": "0.5", "ts": 2.0,
         "kernel": "k", "failed": "Shed"})
    assert norm["ref"] == 7 and norm["parent_ref"] == 3
    assert norm["name"] == "serve.dispatch"
    assert norm["dt"] == 0.5
    assert norm["fields"] == {"kernel": "k", "failed": "Shed"}
    # a torn record still normalizes (dt None -> 0.0)
    assert blame.normalize_record({})["dt"] == 0.0


def test_split_charges_exclusive_time_and_gap():
    spans = [blame.normalize_record(r) for r in _TREE]
    roots = blame.request_roots(spans)
    assert len(roots) == 1
    phases = blame.split(roots[0], blame.index_children(spans))
    assert phases["queue"] == pytest.approx(0.25)
    assert phases["dispatch"] == pytest.approx(0.30)   # 0.40 - 0.10
    assert phases["spill"] == pytest.approx(0.10)
    assert phases["shed_retry"] == pytest.approx(0.05)
    assert phases["other"] == 0.0
    assert phases["gap"] == pytest.approx(0.30)
    assert sum(phases.values()) == pytest.approx(1.0)


def test_nested_root_blames_into_parent_not_table():
    """A serve.request under a cluster.request is a descendant, not a
    second table row."""
    spans = [blame.normalize_record(r) for r in [
        {"span": 2, "parent": 1, "name": "serve.request",
         "t0": 0.0, "dt": 0.4},
        {"span": 1, "parent": None, "name": "cluster.request",
         "t0": 0.0, "dt": 1.0},
    ]]
    roots = blame.request_roots(spans)
    assert [r["name"] for r in roots] == ["cluster.request"]
    phases = blame.split(roots[0], blame.index_children(spans))
    assert phases["other"] == pytest.approx(0.4)
    assert phases["gap"] == pytest.approx(0.6)


def test_analyze_golden_pin():
    """The full analyze() output over the fixed tree — the byte-level
    contract tools/tail_report.py renders.  Loaded through the tool
    (file-path core fallback included) so the refactor's import seam
    is what's under test."""
    tr = _load_tool("tail_report")
    spans = [blame.normalize_record(r) for r in _TREE]
    golden_phases = {"queue": 0.25, "dispatch": 0.3, "spill": 0.1,
                     "shed_retry": 0.05, "other": 0.0, "gap": 0.3}
    assert tr.analyze(spans, top=10) == {
        "spans": 5,
        "requests": 1,
        "slowest": [{
            "name": "serve.request", "ref": 1, "dt": 1.0,
            "req_id": "r1", "trace": None, "sampled": False,
            "promoted": False, "failed": None,
            "phases": golden_phases,
        }],
        "blame_total_s": golden_phases,
        "blame_pct": _TREE_PCT,
    }
    # and the shared-core seam itself: one module, one classifier
    assert tr.analyze is blame.analyze
    assert tr.PHASES == blame.PHASES
    assert tr.ROOT_NAMES == blame.ROOT_NAMES


# ------------------------------------------------------- online engine
def test_unarmed_everything_noops(monkeypatch):
    monkeypatch.delenv("HPNN_BLAME", raising=False)
    obs._reset_for_tests()
    assert not blame.enabled()
    _feed(_TREE)                            # constant-time drop
    assert blame.fleet_doc() is None
    assert blame.sketch_doc() is None
    assert blame.health_doc() == {"armed": False}
    blame.flush()                           # no raise, no publish
    assert not blame._pending and not blame._window


def test_online_fold_matches_offline_split(monkeypatch, tmp_path):
    _arm(monkeypatch, tmp_path, HPNN_BLAME="1")
    _feed(_TREE)
    doc = blame.fleet_doc()
    assert doc["roots"] == 1
    assert doc["pct"] == _TREE_PCT
    assert doc["total_s"]["queue"] == pytest.approx(0.25)
    kern = blame.kernel_doc()
    assert kern["k"]["roots"] == 1
    assert kern["k"]["pct"]["dispatch"] == pytest.approx(30.0)
    health = blame.health_doc()
    assert health["armed"] and health["roots_seen"] == 1
    assert health["pending_spans"] == 0     # subtree fully collected


def test_window_evicts_oldest_roots(monkeypatch, tmp_path):
    _arm(monkeypatch, tmp_path, HPNN_BLAME="1", HPNN_BLAME_WINDOW="16")
    for i in range(20):
        _feed(_TREE, base=i * 10)
    doc = blame.fleet_doc()
    assert doc["roots"] == 16               # not 20: evicted
    assert doc["pct"] == _TREE_PCT          # identical trees: stable
    assert blame.health_doc()["roots_seen"] == 20


def test_window_floor_and_bad_knob(monkeypatch, tmp_path, capsys):
    _arm(monkeypatch, tmp_path, HPNN_BLAME="1", HPNN_BLAME_WINDOW="2")
    assert blame._config()["window"] == blame.WINDOW_FLOOR
    _arm(monkeypatch, tmp_path, HPNN_BLAME="1",
         HPNN_BLAME_WINDOW="lots")
    assert blame._config()["window"] == blame.DEFAULT_WINDOW
    assert "HPNN_BLAME_WINDOW" in capsys.readouterr().err


def test_gauges_publish_on_stride_and_flush(monkeypatch, tmp_path):
    sink = _arm(monkeypatch, tmp_path, HPNN_BLAME="1")
    for i in range(blame._STRIDE - 1):
        _feed(_TREE, base=i * 10)
    gauges = [r for r in _read(sink) if r.get("kind") == "gauge"
              and str(r.get("ev", "")).startswith("blame.")]
    assert not gauges                       # stride not yet elapsed
    _feed(_TREE, base=1000)                 # the stride-th root
    recs = [r for r in _read(sink) if r.get("kind") == "gauge"]
    by_ev = {r["ev"]: r for r in recs if "kernel" not in r}
    for phase, gname in blame.GAUGE_OF.items():
        assert by_ev[gname]["value"] == pytest.approx(
            _TREE_PCT[phase], abs=0.01)
    assert by_ev["blame.window_roots"]["value"] == blame._STRIDE
    # per-kernel rows ride the same names with a kernel field
    kern_rows = [r for r in recs if r.get("kernel") == "k"]
    assert kern_rows
    # flush republished regardless of stride
    blame.flush()
    n_roots_rows = [r for r in _read(sink)
                    if r.get("ev") == "blame.window_roots"]
    assert len(n_roots_rows) == 2


def test_capsule_carries_blame_json(monkeypatch, tmp_path):
    capdir = tmp_path / "capsules"
    _arm(monkeypatch, tmp_path, HPNN_BLAME="1", HPNN_SAMPLE="1",
         HPNN_CAPSULE_DIR=str(capdir), HPNN_CAPSULE_PROFILE_MS="0",
         HPNN_CAPSULE_COOLDOWN_S="0")
    _feed(_TREE)
    man = triggers.capture("unit")
    assert man is not None and "blame.json" in man["files"]
    with open(os.path.join(man["capsule"], "blame.json")) as fp:
        doc = json.load(fp)
    assert doc["roots"] == 1
    assert doc["fleet_pct"]["dispatch"] == pytest.approx(30.0)
    assert doc["kernels"]["k"]["roots"] == 1


def test_orphan_spans_age_out_without_blaming(monkeypatch, tmp_path):
    """A child whose root never closes (crashed request) must neither
    leak the pending buffer nor contribute phase mass."""
    _arm(monkeypatch, tmp_path, HPNN_BLAME="1")
    cap = blame._PENDING_CAP
    for i in range(cap + 50):
        blame.note_record({"span": i + 10, "parent": None,
                           "name": "serve.orphan", "t0": 0.0,
                           "dt": 0.1})
    assert len(blame._pending) == cap
    assert blame.fleet_doc()["roots"] == 0


# ---------------------------------------------- online/offline parity
def test_online_offline_agreement_within_1pp(monkeypatch, tmp_path):
    """The ISSUE's closing claim: sampled serve traffic through a real
    Session, the rolling online split vs the offline tail_report over
    the very same sink — every phase within 1pp."""
    sink = _arm(monkeypatch, tmp_path, HPNN_SAMPLE="1", HPNN_BLAME="1",
                HPNN_BLAME_WINDOW="128")
    k, _ = kernel_mod.generate(7, 8, [5], 2)
    sess = serve.Session(max_batch=8, n_buckets=2, max_wait_ms=0.5)
    sess.register_kernel("k", k)
    for _ in range(24):
        sess.infer("k", np.zeros(8))
    sess.close()
    online = blame.fleet_doc()
    assert online["roots"] == 24
    obs.configure(None)
    tr = _load_tool("tail_report")
    offline = tr.analyze(tr.load_spans([str(sink)]), top=5)
    assert offline["requests"] == 24
    for phase in blame.PHASES:
        assert online["pct"][phase] == pytest.approx(
            offline["blame_pct"][phase], abs=1.0), phase
