"""Head-to-head parity vs the actually-built reference C binary.

Builds the reference's ``train_nn`` serial-only (gcc, no OMP/BLAS/MPI)
from /root/reference, runs it and our f64 parity mode on the same
seeded workload, and compares:

* the complete training token stream (shuffle order, ``init=``, OK/NO,
  ``N_ITER=``, ``final=``, SUCCESS!/FAIL!) — must be IDENTICAL;
* ``kernel.tmp`` (the generated initial weights) — must be
  byte-identical (%17.15f round-trip of a bit-identical glibc stream);
* ``kernel.opt`` (after training) — abs-sum agreement to the
  reference's own cross-backend bar (~1e-12/weight-matrix,
  ref: /root/reference/ChangeLog:33-38; summation order inside XLA's
  f64 dots differs from C's sequential loops, so bitwise equality is
  not expected after ~100k iterations).

Skipped when /root/reference or a C compiler is unavailable.
"""

import os
import shutil
import subprocess
import sys

import numpy as np
import pytest

REF = "/root/reference"

pytestmark = pytest.mark.skipif(
    not (os.path.isdir(REF) and shutil.which("gcc")),
    reason="reference sources or gcc unavailable",
)


def _build(tmp_path_factory, main_src: str, name: str):
    d = tmp_path_factory.mktemp("refbuild")
    exe = d / name
    res = subprocess.run(
        [
            "gcc", "-O2", f"-I{REF}/include",
            f"{REF}/src/libhpnn.c", f"{REF}/src/ann.c", f"{REF}/src/snn.c",
            main_src, "-lm", "-o", str(exe),
        ],
        capture_output=True,
        text=True,
    )
    if res.returncode != 0:
        pytest.skip(f"reference build failed: {res.stderr[:500]}")
    return exe


@pytest.fixture(scope="module")
def ref_binary(tmp_path_factory):
    return _build(tmp_path_factory, f"{REF}/tests/train_nn.c", "train_nn_ref")


@pytest.fixture(scope="module")
def ref_run_binary(tmp_path_factory):
    return _build(tmp_path_factory, f"{REF}/tests/run_nn.c", "run_nn_ref")


def _workload(d, n=4, n_in=8, n_out=3, nn_type="ANN", train="BP", snn=False):
    sdir = d / "samples"
    sdir.mkdir()
    rng = np.random.RandomState(11)
    for i in range(n):
        x = rng.uniform(-1, 1, n_in)
        t = np.full(n_out, 0.0 if snn else -1.0)
        t[i % n_out] = 1.0
        with open(sdir / f"s{i:05d}.txt", "w") as fp:
            fp.write(f"[input] {n_in}\n" + " ".join(f"{v:7.5f}" for v in x) + "\n")
            fp.write(f"[output] {n_out}\n" + " ".join(f"{v:.1f}" for v in t) + "\n")
    (d / "nn.conf").write_text(
        f"[name] P\n[type] {nn_type}\n[init] generate\n[seed] 777\n"
        f"[input] {n_in}\n[hidden] 6\n[output] {n_out}\n[train] {train}\n"
        "[sample_dir] ./samples\n[test_dir] ./samples\n"
    )


def _tokens(text, what="TRAINING FILE"):
    return [ln for ln in text.splitlines() if what in ln]


def _run_ours(tmp_path, cli_main, argv):
    import contextlib
    import io

    from hpnn_tpu.utils import logging as log

    cwd = os.getcwd()
    buf = io.StringIO()
    old_verbose = log.get_verbose()
    try:
        os.chdir(tmp_path)
        with contextlib.redirect_stdout(buf):
            assert cli_main(argv) == 0
    finally:
        os.chdir(cwd)
        log.set_verbose(old_verbose)
    return buf.getvalue()


@pytest.mark.parametrize("nn_type,train,snn", [
    ("ANN", "BP", False),
    ("ANN", "BPM", False),
    ("SNN", "BP", True),
    ("SNN", "BPM", True),
])
def test_training_parity_vs_reference(ref_binary, tmp_path, nn_type, train, snn):
    from hpnn_tpu.cli import train_nn as cli
    from hpnn_tpu.fileio import kernel_format

    _workload(tmp_path, nn_type=nn_type, train=train, snn=snn)
    res = subprocess.run(
        [str(ref_binary), "-v", "-v", "-v", "nn.conf"],
        cwd=tmp_path, capture_output=True, text=True, timeout=500,
    )
    ref_out = res.stdout + res.stderr
    assert res.returncode == 0, f"reference run failed:\n{ref_out[:2000]}"
    ref_tmp = (tmp_path / "kernel.tmp").read_text()
    ref_opt = (tmp_path / "kernel.opt").read_text()
    (tmp_path / "kernel.tmp").unlink()
    (tmp_path / "kernel.opt").unlink()

    ours_out = _run_ours(tmp_path, cli.main, ["-v", "-v", "-v", "nn.conf"])

    assert _tokens(ours_out) == _tokens(ref_out)
    assert (tmp_path / "kernel.tmp").read_text() == ref_tmp

    # trained weights: reference's cross-backend bar
    _, ours_w = kernel_format.load_kernel(str(tmp_path / "kernel.opt"))
    (tmp_path / "ref_opt.txt").write_text(ref_opt)
    _, ref_w = kernel_format.load_kernel(str(tmp_path / "ref_opt.txt"))
    for a, b in zip(ref_w, ours_w):
        assert abs(np.abs(a).sum() - np.abs(b).sum()) < 1e-10
        assert np.abs(a - b).max() < 1e-10


@pytest.mark.parametrize("nn_type,snn", [("ANN", False), ("SNN", True)])
def test_eval_parity_vs_reference(ref_binary, ref_run_binary, tmp_path,
                                  nn_type, snn):
    """run_nn verdict tokens match the reference binary's, including the
    SNN BEST CLASS line."""
    from hpnn_tpu.cli import run_nn as cli

    _workload(tmp_path, nn_type=nn_type, snn=snn)
    res = subprocess.run(
        [str(ref_binary), "nn.conf"],  # train silently, writes kernel.opt
        cwd=tmp_path, capture_output=True, text=True, timeout=500,
    )
    assert res.returncode == 0
    conf = (tmp_path / "nn.conf").read_text().replace(
        "[init] generate", "[init] kernel.opt"
    )
    (tmp_path / "cont.conf").write_text(conf)

    res = subprocess.run(
        [str(ref_run_binary), "-v", "-v", "cont.conf"],
        cwd=tmp_path, capture_output=True, text=True, timeout=300,
    )
    ref_out = res.stdout + res.stderr
    assert res.returncode == 0, ref_out[:2000]

    ours_out = _run_ours(tmp_path, cli.main, ["-v", "-v", "cont.conf"])
    assert _tokens(ours_out, "TESTING FILE") == _tokens(ref_out, "TESTING FILE")
    assert _tokens(ref_out, "TESTING FILE")  # non-empty