"""Promotion WAL + atomic checkpoints (hpnn_tpu/online/wal.py,
hpnn_tpu/fileio/checkpoint.py, docs/resilience.md).

Covers the bitwise commit/restore round trip (mixed dtypes included),
per-version checkpoint pruning, replay's skip ladder (stat-mismatched
``sig``, torn ``torn``, non-checkpoint ``magic``) falling back to the
previous committed version, torn-tail WAL lines, ``kernel.load``
dispatching on checkpoint files, ``OnlineSession`` replay wiring
(bitwise weights, registry staleness signature kept live, health doc),
the promoter's persist-on-promote, and the crash rehearsal itself: a
subprocess SIGKILLed at the ``online.checkpoint`` seam mid-promotion
restarts into the last *committed* weights, bitwise.
"""

import hashlib
import os
import subprocess
import sys
import textwrap

import numpy as np
import pytest

from hpnn_tpu import online
from hpnn_tpu.fileio import checkpoint as ckpt_mod
from hpnn_tpu.models import kernel as kernel_mod
from hpnn_tpu.online.wal import PromotionWAL

ROOT = os.path.abspath(os.path.join(os.path.dirname(__file__), ".."))


def _weights(seed, scale=1.0):
    k, _ = kernel_mod.generate(seed, 8, [5], 2)
    return tuple(np.asarray(w) * scale for w in k.weights)


def _sha(weights):
    h = hashlib.sha256()
    for w in weights:
        h.update(np.ascontiguousarray(np.asarray(w)).tobytes())
    return h.hexdigest()


def _assert_bitwise(got, want):
    assert len(got) == len(want)
    for g, w in zip(got, want):
        g, w = np.asarray(g), np.asarray(w)
        assert g.dtype == w.dtype and g.shape == w.shape
        assert g.tobytes() == w.tobytes()


def test_commit_restore_bitwise_roundtrip(tmp_path):
    wal = PromotionWAL(str(tmp_path))
    w1, w2 = _weights(1), _weights(2)
    wal.commit("k", w1, version=1)
    rec = wal.commit("k", w2, version=2, reason="promote", step=7)
    assert rec["ckpt"] == "k.v2.ckpt"
    got, got_rec = wal.restore("k")
    _assert_bitwise(got, w2)
    assert got_rec["version"] == 2 and got_rec["step"] == 7
    assert wal.last_committed("k")["version"] == 2
    assert wal.names() == ["k"]
    assert wal.doc()["records"] == 2


def test_mixed_dtype_weights_survive_bitwise(tmp_path):
    wal = PromotionWAL(str(tmp_path))
    ws = (np.linspace(0, 1, 6, dtype=np.float32).reshape(2, 3),
          np.arange(4, dtype=np.float64) / 7.0,
          np.array([[1, 2], [3, 4]], dtype=np.int32))
    wal.commit("m", ws, version=1)
    got, _ = wal.restore("m")
    _assert_bitwise(got, ws)


def test_prune_keeps_newest_three_versions(tmp_path):
    wal = PromotionWAL(str(tmp_path))
    for v in range(1, 6):
        wal.commit("k", _weights(v), version=v)
    on_disk = sorted(fn for fn in os.listdir(str(tmp_path))
                     if fn.endswith(".ckpt"))
    assert on_disk == ["k.v3.ckpt", "k.v4.ckpt", "k.v5.ckpt"]
    got, rec = wal.restore("k")
    assert rec["version"] == 5
    _assert_bitwise(got, _weights(5))


def test_torn_checkpoint_falls_back_to_previous(tmp_path):
    wal = PromotionWAL(str(tmp_path))
    w1, w2 = _weights(1), _weights(2)
    wal.commit("k", w1, version=1)
    wal.commit("k", w2, version=2)
    # corrupt v2's payload in place, byte-for-byte same size, and put
    # the recorded mtime back — the stat signature matches but the
    # sha256 integrity check does not: the "torn" skip path
    path = str(tmp_path / "k.v2.ckpt")
    st = os.stat(path)
    with open(path, "r+b") as fp:
        fp.seek(-8, os.SEEK_END)
        fp.write(b"\xde\xad\xbe\xef\xde\xad\xbe\xef")
    os.utime(path, ns=(st.st_atime_ns, st.st_mtime_ns))
    got, rec = wal.restore("k")
    assert rec["version"] == 1
    _assert_bitwise(got, w1)
    # last_committed's cheaper check (magic only) still sees v2; the
    # full restore is the one that walks past the torn payload
    with pytest.raises(ckpt_mod.CheckpointError):
        ckpt_mod.load_checkpoint(path)


def test_rewritten_checkpoint_skipped_by_signature(tmp_path):
    wal = PromotionWAL(str(tmp_path))
    w1 = _weights(1)
    wal.commit("k", w1, version=1)
    wal.commit("k", _weights(2), version=2)
    # rewrite v2's file AFTER its commit (an intact checkpoint, but
    # not the bytes the record fsync'd) — replay must not trust it
    ckpt_mod.dump_checkpoint(str(tmp_path / "k.v2.ckpt"), "k",
                             _weights(9), version=2)
    got, rec = wal.restore("k")
    assert rec["version"] == 1
    _assert_bitwise(got, w1)
    assert wal.last_committed("k")["version"] == 1


def test_torn_tail_wal_line_is_skipped(tmp_path):
    wal = PromotionWAL(str(tmp_path))
    wal.commit("k", _weights(1), version=1)
    with open(wal.path, "a") as fp:
        fp.write('{"ev": "wal.commit", "kernel": "k", "vers')  # crash
    assert len(wal.records()) == 1
    assert wal.last_committed("k")["version"] == 1


def test_kernel_load_dispatches_on_checkpoint_files(tmp_path):
    ws = _weights(4)
    path = str(tmp_path / "k.v3.ckpt")
    ckpt_mod.dump_checkpoint(path, "k", ws, version=3)
    name, k = kernel_mod.load(path)
    assert name == "k"
    _assert_bitwise(k.weights, ws)


def _mk_osess(wal=None, **kw):
    defaults = dict(
        serve_kwargs=dict(max_batch=8, n_buckets=2, max_wait_ms=1.0),
        rows=16, batch=8, epochs=2, interval_s=60.0, holdout=4,
        gate=online.Gate(margin=-10.0, watch_s=30.0), seed=5, wal=wal)
    defaults.update(kw)
    return online.OnlineSession(**defaults)


def test_online_session_replays_wal_bitwise(tmp_path):
    committed = _weights(11, scale=0.5)
    PromotionWAL(str(tmp_path)).commit("r", committed, version=4,
                                       reason="promote")
    osess = _mk_osess(wal=PromotionWAL(str(tmp_path)))
    try:
        fresh, _ = kernel_mod.generate(7, 8, [5], 2)
        osess.add_kernel("r", fresh)
        entry = osess.serve.registry.get("r")
        _assert_bitwise(entry.kernel.weights, committed)
        assert osess.restored == {"r": 4}
        # the restored entry is checkpoint-backed: the registry's
        # hot-reload staleness machinery keeps working on it, which
        # is what the reload drill leans on
        assert entry.path.endswith("r.v4.ckpt")
        assert osess.serve.maybe_reload("r") is False
        newer = _weights(12, scale=0.25)
        ckpt_mod.dump_checkpoint(entry.path, "r", newer, version=5)
        assert osess.serve.maybe_reload("r") is True
        _assert_bitwise(osess.serve.registry.get("r").kernel.weights,
                        newer)
        health = osess.health_doc()
        assert health["wal"]["restored"] == {"r": 4}
        assert "weights_sha" in health["kernels"]["r"]
    finally:
        osess.close()


def test_promoter_persists_promotions(tmp_path):
    wal = PromotionWAL(str(tmp_path))
    osess = _mk_osess(wal=wal)
    try:
        k, _ = kernel_mod.generate(7, 8, [5], 2)
        osess.add_kernel("p", k)
        rng = np.random.RandomState(3)
        X = rng.uniform(0.0, 1.0, (48, 8))
        osess.feed(X, np.tanh(X[:, :2]))
        summary = osess.tick()
        assert summary["promoted"] == 1
        rec = wal.last_committed("p")
        assert rec is not None and rec["reason"] == "promote"
        got, _ = wal.restore("p")
        _assert_bitwise(
            got, osess.serve.registry.get("p").kernel.weights)
        # rollback is durable too
        osess.rollback("p")
        assert wal.last_committed("p")["reason"].startswith("rollback")
    finally:
        osess.close()


_CRASH_CHILD = textwrap.dedent("""\
    import os, sys
    os.environ["JAX_PLATFORMS"] = "cpu"
    os.environ["HPNN_CHAOS"] = "kill@online.checkpoint:after=1"
    sys.path.insert(0, {root!r})
    import numpy as np
    from hpnn_tpu import online
    from hpnn_tpu.models import kernel as kernel_mod
    from hpnn_tpu.online.wal import PromotionWAL

    wal_dir, sha_path = sys.argv[1], sys.argv[2]
    osess = online.OnlineSession(
        serve_kwargs=dict(max_batch=8, n_buckets=2, max_wait_ms=1.0),
        rows=16, batch=8, epochs=1, interval_s=60.0, holdout=4,
        gate=online.Gate(margin=-10.0, watch_s=30.0), seed=5,
        wal=PromotionWAL(wal_dir))
    k, _ = kernel_mod.generate(7, 8, [5], 2)
    osess.add_kernel("c", k)
    rng = np.random.RandomState(3)
    for round_no in range(6):
        X = rng.uniform(0.0, 1.0, (48, 8))
        osess.feed(X, np.tanh(X[:, :2]))
        summary = osess.tick()
        if summary["promoted"] and not os.path.exists(sha_path):
            # first promotion committed (the chaos kill fires on the
            # SECOND pass through the online.checkpoint seam): record
            # the resident weights the WAL must resurrect
            import hashlib
            h = hashlib.sha256()
            for w in osess.serve.registry.get("c").kernel.weights:
                h.update(np.ascontiguousarray(np.asarray(w)).tobytes())
            with open(sha_path, "w") as fp:
                fp.write(h.hexdigest())
                fp.flush()
                os.fsync(fp.fileno())
    sys.exit(3)  # chaos never fired — the test must fail on this
""")


def test_sigkill_mid_promotion_restarts_bitwise(tmp_path):
    """The acceptance crash rehearsal, in miniature: a child process
    promotes once (durably), then is SIGKILLed at the
    ``online.checkpoint`` seam — after the second promotion installed
    in memory, before its WAL commit.  A fresh session over the same
    WAL dir must come back with the *committed* weights, bitwise."""
    wal_dir = str(tmp_path / "wal")
    sha_path = str(tmp_path / "committed.sha")
    script = tmp_path / "crash_child.py"
    script.write_text(_CRASH_CHILD.format(root=ROOT))
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    env.pop("HPNN_WAL_DIR", None)
    proc = subprocess.run(
        [sys.executable, str(script), wal_dir, sha_path],
        env=env, capture_output=True, text=True, timeout=300)
    assert proc.returncode == -9, (
        f"child was not SIGKILLed (rc={proc.returncode}):\n"
        f"{proc.stderr[-2000:]}")
    assert os.path.exists(sha_path), "child died before promoting once"
    with open(sha_path) as fp:
        want_sha = fp.read().strip()

    wal = PromotionWAL(wal_dir)
    rec = wal.last_committed("c")
    assert rec is not None and rec["version"] >= 1
    osess = _mk_osess(wal=PromotionWAL(wal_dir))
    try:
        fresh, _ = kernel_mod.generate(99, 8, [5], 2)
        osess.add_kernel("c", fresh)
        got = tuple(np.asarray(w) for w in
                    osess.serve.registry.get("c").kernel.weights)
        assert _sha(got) == want_sha
        assert osess.restored == {"c": rec["version"]}
    finally:
        osess.close()
