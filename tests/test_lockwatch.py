"""Lock-order watchdog (hpnn_tpu/obs/lockwatch.py, docs/analysis.md).

Acceptance bar (ISSUE): a 2-lock order cycle under HPNN_LOCKWATCH=1
is detected and reported with BOTH acquisition stacks.  Also proven
here: unarmed zero-overhead (plain threading.Lock back), Condition
compatibility (the serve batcher wraps its watched lock in one), the
wired serve/online objects really carry watched locks under their
documented role names, and live traffic through them leaves the
graph acyclic so the conftest cycle gate passes.
"""

import threading

import pytest

from hpnn_tpu.models import kernel as kernel_mod
from hpnn_tpu.obs import lockwatch


def _arm(monkeypatch):
    monkeypatch.setenv(lockwatch.ENV_KNOB, "1")
    lockwatch._reset_for_tests()


def _kernel(seed=7):
    k, _ = kernel_mod.generate(seed, 8, [5], 2)
    return k


# --------------------------------------------------------------- unarmed
def test_unarmed_returns_plain_lock(monkeypatch):
    monkeypatch.delenv(lockwatch.ENV_KNOB, raising=False)
    lockwatch._reset_for_tests()
    lk = lockwatch.lock("x")
    assert not isinstance(lk, lockwatch._WatchedLock)
    with lk:                      # still a perfectly good lock
        pass
    assert lockwatch.edges() == {}
    lockwatch.check()             # vacuous: nothing recorded


def test_unarmed_memoizes_one_env_read(monkeypatch):
    monkeypatch.delenv(lockwatch.ENV_KNOB, raising=False)
    lockwatch._reset_for_tests()
    assert lockwatch.enabled() is False
    # flipping env after the memo must not re-arm mid-process
    monkeypatch.setenv(lockwatch.ENV_KNOB, "1")
    assert lockwatch.enabled() is False
    lockwatch._reset_for_tests()  # explicit reset re-reads
    assert lockwatch.enabled() is True


# ----------------------------------------------------------------- armed
def test_armed_records_edges_no_cycle(monkeypatch):
    _arm(monkeypatch)
    a, b = lockwatch.lock("a"), lockwatch.lock("b")
    with a:
        with b:
            pass
    assert ("a", "b") in lockwatch.edges()
    assert ("b", "a") not in lockwatch.edges()
    assert lockwatch.cycles() == []
    lockwatch.check()             # consistent order: passes


def test_reentry_is_not_an_ordering(monkeypatch):
    _arm(monkeypatch)
    a1, a2 = lockwatch.lock("a"), lockwatch.lock("a")  # same role
    with a1:
        with a2:                  # distinct objects, same name
            pass
    assert lockwatch.edges() == {}


def test_two_lock_cycle_detected_with_both_stacks(monkeypatch):
    """The ISSUE acceptance criterion: a -> b then b -> a raises with
    each edge's two acquisition stacks in the report."""
    _arm(monkeypatch)
    a, b = lockwatch.lock("serve.demo.a"), lockwatch.lock("serve.demo.b")

    def take_a_then_b():
        with a:
            with b:
                pass

    def take_b_then_a():
        with b:
            with a:
                pass

    take_a_then_b()
    take_b_then_a()               # no deadlock: order evidence only
    assert lockwatch.cycles() != []
    with pytest.raises(lockwatch.LockOrderError) as exc:
        lockwatch.check()
    text = str(exc.value)
    assert "serve.demo.a -> serve.demo.b" in text
    assert "serve.demo.b -> serve.demo.a" in text
    # both stacks per edge: the two call sites that built the cycle
    assert "take_a_then_b" in text
    assert "take_b_then_a" in text
    assert text.count("acquired at") >= 4   # 2 edges x 2 stacks
    lockwatch._reset_for_tests()  # don't trip the conftest gate


def test_cycle_across_threads(monkeypatch):
    """Order evidence composes across threads — the scenario a real
    deadlock needs, caught without any actual contention."""
    _arm(monkeypatch)
    a, b = lockwatch.lock("t.a"), lockwatch.lock("t.b")
    with a:
        with b:
            pass

    def other():
        with b:
            with a:
                pass

    t = threading.Thread(target=other)
    t.start()
    t.join()
    assert lockwatch.cycles() != []
    lockwatch._reset_for_tests()


def test_condition_over_watched_lock(monkeypatch):
    """threading.Condition(lockwatch.lock(...)) must work armed — the
    serve batcher's exact shape."""
    _arm(monkeypatch)
    lk = lockwatch.lock("cond.demo")
    cond = threading.Condition(lk)
    hits = []

    def waiter():
        with cond:
            while not hits:
                cond.wait(timeout=5)

    t = threading.Thread(target=waiter)
    t.start()
    with cond:
        hits.append(1)
        cond.notify()
    t.join(timeout=5)
    assert not t.is_alive()
    assert lockwatch.cycles() == []


# ------------------------------------------------------- wired lock roles
def test_wired_objects_carry_watched_roles(monkeypatch, tmp_path):
    _arm(monkeypatch)
    from hpnn_tpu.online.promote import Promoter
    from hpnn_tpu.online.wal import PromotionWAL
    from hpnn_tpu.serve import batcher as batcher_mod
    from hpnn_tpu.serve.registry import Registry

    reg = Registry()
    wal = PromotionWAL(str(tmp_path))
    bat = batcher_mod.Batcher(lambda p: list(p), max_batch=4, start=False)
    prom = Promoter(session=None)
    assert isinstance(reg._lock, lockwatch._WatchedLock)
    assert reg._lock.name == "serve.registry"
    assert wal._lock.name == "online.wal"
    assert bat._lock.name == "serve.batcher"
    assert prom._lock.name == "online.promote"


def test_armed_live_traffic_stays_acyclic(monkeypatch, tmp_path):
    """Drive real registry/batcher/WAL traffic with the watchdog armed:
    everything behaves, and the acquisition graph the traffic leaves
    behind has no cycles (so the conftest gate would pass)."""
    _arm(monkeypatch)
    from hpnn_tpu.online.wal import PromotionWAL
    from hpnn_tpu.serve import batcher as batcher_mod
    from hpnn_tpu.serve.registry import Registry

    reg = Registry()
    k = _kernel()
    e = reg.register("k", k)
    assert reg.get("k") is e

    bat = batcher_mod.Batcher(lambda p: list(p), max_batch=8, start=False)
    reqs = [bat.submit(i, rows=1) for i in range(3)]
    assert bat.drain_once() == 3
    assert [bat.result(r, timeout_s=0) for r in reqs] == [0, 1, 2]

    wal = PromotionWAL(str(tmp_path))
    rec = wal.commit("k", k.weights, version=1)
    assert rec["ev"] == "wal.commit"

    assert lockwatch.cycles() == []
    lockwatch.check()
