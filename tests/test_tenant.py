"""Multi-tenant hosting subsystem (hpnn_tpu/tenant/, docs/tenancy.md).

Acceptance bar (ISSUE): a paged-out-then-paged-in kernel answers
**bitwise** identically to one never evicted; a promotion landing on a
paged-out kernel pages it in first and bumps its version; an infer
racing a page-out blocks on the pager and pages back in — never a
KeyError/404.  Around that core: registry sharding (stable hash,
distinct watched locks, O(1) census), quota grammar + token-bucket
admission with a fake clock, the HTTP edge (``X-Tenant`` routing, the
429 body naming the tenant, ``/tenantz``, health summarization past
``HEALTH_LIST_MAX``), the ``--tenant`` sink lint both accepting a live
run and biting on every schema break, and the loadgen Zipf tenant mix.
"""

import http.client
import importlib.util
import json
import os
import threading
import zlib

import numpy as np
import pytest

from hpnn_tpu import obs, serve
from hpnn_tpu.models import ann, kernel as kernel_mod
from hpnn_tpu.serve.server import make_server
from hpnn_tpu.tenant.host import TenantSession, scoped
from hpnn_tpu.tenant.pager import Pager, PagingError
from hpnn_tpu.tenant.quota import (QuotaEnforcer, QuotaExceeded,
                                   TenantSpec, parse_tenants)
from hpnn_tpu.tenant.shards import ShardedRegistry, shard_of

ROOT = os.path.abspath(os.path.join(os.path.dirname(__file__), ".."))


def _kernel(seed=7, n_in=8, hiddens=(5,), n_out=2):
    k, _ = kernel_mod.generate(seed, n_in, list(hiddens), n_out)
    return k


def _direct_ann(kernel, rows):
    return np.stack([np.asarray(ann.run(kernel.weights, x))
                     for x in np.atleast_2d(rows)])


def _session(tmp_path, *, resident_max=0, page_dir=None, tenants=None,
             fleet=False, **kw):
    """A small TenantSession: tiny bucket menu, short waits, paging
    warmup off (compiles happen lazily on dispatch — the tests assert
    weights parity, not compile latency)."""
    return TenantSession(max_batch=8, n_buckets=2, max_wait_ms=0.5,
                         fleet=fleet, shards=4,
                         resident_max=resident_max, page_dir=page_dir,
                         tenants=tenants, page_warmup=False, **kw)


class FakeClock:
    def __init__(self):
        self.t = 100.0

    def __call__(self):
        return self.t

    def advance(self, dt):
        self.t += dt


def _read_sink(path):
    with open(path) as fp:
        return [json.loads(ln) for ln in fp if ln.strip()]


def _load_tool(name):
    spec = importlib.util.spec_from_file_location(
        name, os.path.join(ROOT, "tools", f"{name}.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


# ------------------------------------------------------------ sharding
def test_shard_of_is_stable_crc32_and_spreads():
    # replicas must shard identically across processes: the hash is
    # crc32 of the utf-8 name, never PYTHONHASHSEED-poisoned hash()
    assert shard_of("acme:k", 16) == zlib.crc32(b"acme:k") % 16
    assert shard_of("acme:k", 16) == shard_of("acme:k", 16)
    counts = [0] * 16
    for i in range(1000):
        counts[shard_of(f"t{i % 7}:kernel-{i}", 16)] += 1
    assert min(counts) > 0            # no empty stripe at 1000 names
    assert max(counts) < 3 * (1000 // 16)   # no degenerate pile-up


def test_sharded_registry_surface_census_and_distinct_locks(
        monkeypatch):
    from hpnn_tpu.obs import lockwatch

    # armed, the stripes must register as DISTINCT watched locks (the
    # lock-order watchdog sees serve.registry.s0..s3, not one name)
    monkeypatch.setenv(lockwatch.ENV_KNOB, "1")
    lockwatch._reset_for_tests()
    try:
        reg = ShardedRegistry(4)
        lock_names = {s._lock.name for s in reg.shards}
        assert lock_names == {f"serve.registry.s{i}" for i in range(4)}
    finally:
        monkeypatch.delenv(lockwatch.ENV_KNOB, raising=False)
        lockwatch._reset_for_tests()

    reg = ShardedRegistry(4)
    names = [f"t{i % 3}:k{i}" for i in range(40)]
    for i, name in enumerate(names):
        reg.register(name, _kernel(seed=100 + i))
    assert reg.count() == 40
    assert reg.names() == sorted(names)
    assert reg.get(names[7]).version == 0
    census = reg.census()
    assert census["count"] == 40 and census["shards"] == 4
    assert census["shard_min"] >= 1
    assert census["shard_min"] <= census["shard_max"]
    sample = reg.sample(16)
    assert len(sample) == 16 and set(sample) <= set(names)
    reg.unregister(names[0])
    assert reg.count() == 39
    with pytest.raises(KeyError):
        reg.get(names[0])
    with pytest.raises(ValueError):
        ShardedRegistry(0)


# ------------------------------------------------------------ quota
def test_parse_tenants_grammar():
    specs = parse_tenants(
        "acme=gold:rate=50:inflight=8,hog=bronze:rate=5:burst=2,best")
    assert specs["acme"] == TenantSpec("acme", "gold", 50.0, 8, 0.25)
    assert specs["hog"].rate_rps == 5.0 and specs["hog"].burst_s == 2.0
    assert specs["best"].slo_class == "bronze"      # bare name: default
    assert specs["acme"].target_ms == 25.0
    assert specs["hog"].target_ms == 400.0
    # junk raises — a silently dropped quota is an isolation hole
    for bad in ("x=platinum", "x=gold:wat", "x=gold:speed=9",
                "=gold:rate=1"):
        with pytest.raises(ValueError):
            parse_tenants(bad)


def test_quota_rate_bucket_and_inflight_with_fake_clock():
    clk = FakeClock()
    q = QuotaEnforcer(
        {"metered": TenantSpec("metered", "silver", rate_rps=2.0,
                               burst_s=0.5),
         "narrow": TenantSpec("narrow", "gold", max_inflight=1)},
        clock=clk)
    # rate: burst = max(1, 2*0.5) = 1 token — one admit, then shed
    q.admit("metered")
    q.release("metered")
    with pytest.raises(QuotaExceeded) as ei:
        q.admit("metered")
    assert ei.value.reason == "quota" and ei.value.tenant == "metered"
    assert ei.value.retry_after_s > 0
    clk.advance(0.5)                  # refill: 0.5s * 2rps = 1 token
    q.admit("metered")
    q.release("metered")
    # inflight: the slot frees on release, not on time
    q.admit("narrow")
    with pytest.raises(QuotaExceeded) as ei:
        q.admit("narrow")
    assert "inflight" in str(ei.value)
    q.release("narrow")
    q.admit("narrow")
    q.release("narrow")
    # an undeclared tenant degrades to bronze/uncapped best-effort
    for _ in range(50):
        q.admit("anon")
        q.release("anon")
    assert q.spec("anon") == TenantSpec("anon")
    doc = q.health_doc()
    assert doc["metered"]["slo_class"] == "silver"
    assert doc["metered"]["shed_rate"] > 0
    assert doc["narrow"]["inflight"] == 0
    assert set(doc) == {"metered", "narrow", "anon"}


def test_quota_record_publishes_windowed_p99():
    clk = FakeClock()
    q = QuotaEnforcer({"t": TenantSpec("t", "gold")}, clock=clk)
    for ms in range(1, 11):
        q.admit("t")
        q.release("t")
        q.record("t", ms / 1000.0)
    assert q.p99_ms("t") == pytest.approx(10.0)
    clk.advance(60.0)                 # the 10s window forgets it all
    assert q.p99_ms("t") is None


# ------------------------------------------------------------ paging
def test_page_round_trip_is_bitwise_and_version_pinned(tmp_path):
    store = str(tmp_path / "store")
    sess = _session(tmp_path, resident_max=1, page_dir=store)
    try:
        ka, kb = _kernel(seed=21), _kernel(seed=22)
        x = np.linspace(-1.0, 1.0, 8)
        sess.register_for("t", "a", ka, warmup=False)
        before = np.asarray(sess.infer_for("t", "a", x))
        assert np.array_equal(before, _direct_ann(ka, x)[0])
        v_before = sess.registry.get(scoped("t", "a")).version

        sess.register_for("t", "b", kb, warmup=False)   # evicts a
        assert sess.pager.is_paged(scoped("t", "a"))
        assert not sess.pager.is_resident(scoped("t", "a"))
        # the checkpoint + index landed in the object store
        assert os.path.isdir(os.path.join(store, "objects"))
        assert os.listdir(os.path.join(store, "index"))

        after = np.asarray(sess.infer_for("t", "a", x))  # pages in
        assert np.array_equal(after, before)             # bitwise
        entry = sess.registry.get(scoped("t", "a"))
        assert entry.version == v_before                 # pinned
        assert np.array_equal(
            np.concatenate([w.ravel() for w in entry.kernel.weights]),
            np.concatenate([w.ravel() for w in ka.weights]))
        assert sess.pager.health_doc()["page_ins"] == 1
        assert sess.pager.health_doc()["page_outs"] >= 1
    finally:
        sess.close()


def test_promotion_on_paged_out_kernel_pages_in_first(tmp_path):
    store = str(tmp_path / "store")
    sess = _session(tmp_path, resident_max=1, page_dir=store)
    try:
        name = scoped("t", "a")
        ka, ka2, kb = _kernel(seed=31), _kernel(seed=32), _kernel(seed=33)
        sess.register_for("t", "a", ka, warmup=False)
        sess.register_for("t", "b", kb, warmup=False)   # a paged out
        assert sess.pager.is_paged(name)

        entry = sess.install_kernel(name, ka2, warmup=False)
        assert entry.version == 1     # chained off the real lineage
        assert sess.pager.is_resident(name)
        assert not sess.pager.is_paged(name)
        x = np.linspace(-1.0, 1.0, 8)
        out = np.asarray(sess.infer_for("t", "a", x))
        assert np.array_equal(out, _direct_ann(ka2, x)[0])
    finally:
        sess.close()


def test_concurrent_infer_racing_page_out_never_404(tmp_path):
    """Three threads alternate over two kernels sharing one resident
    slot — every request forces the other kernel's eviction, so each
    infer races a page-out.  Pins must make that race invisible: no
    KeyError, and every answer bitwise-correct for its kernel."""
    store = str(tmp_path / "store")
    sess = _session(tmp_path, resident_max=1, page_dir=store)
    try:
        kernels = {"a": _kernel(seed=41), "b": _kernel(seed=42)}
        x = np.linspace(-1.0, 1.0, 8)
        want = {n: _direct_ann(k, x)[0] for n, k in kernels.items()}
        for n, k in kernels.items():
            sess.register_for("t", n, k, warmup=False)
        errs: list = []

        def client(i):
            try:
                for j in range(12):
                    n = "a" if (i + j) % 2 == 0 else "b"
                    out = np.asarray(
                        sess.infer_for("t", n, x, timeout_s=30.0))
                    if not np.array_equal(out, want[n]):
                        errs.append((n, "mismatch"))
            except Exception as exc:  # collected, asserted empty below
                errs.append(repr(exc))

        threads = [threading.Thread(target=client, args=(i,))
                   for i in range(3)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        assert not errs, errs
        doc = sess.pager.health_doc()
        assert doc["page_ins"] >= 2   # the race actually happened
        assert doc["resident"] <= 1 + doc["pinned"]
    finally:
        sess.close()


def test_cap_without_store_raises_and_pins_hold_over_cap(tmp_path):
    with pytest.raises(PagingError):
        Pager(ShardedRegistry(2), engine=None, resident_max=4,
              page_dir=None)
    store = str(tmp_path / "store")
    sess = _session(tmp_path, resident_max=1, page_dir=store)
    try:
        sess.register_for("t", "a", _kernel(seed=51), warmup=False)
        sess.register_for("t", "b", _kernel(seed=52), warmup=False)
        with sess.pager.pin(scoped("t", "b")):    # b in, a out, b pinned
            with sess.pager.pin(scoped("t", "a")):
                # both pinned: the cap yields, nothing is evictable
                assert sess.pager.is_resident(scoped("t", "a"))
                assert sess.pager.is_resident(scoped("t", "b"))
                doc = sess.pager.health_doc()
                assert doc["resident"] == 2 and doc["pinned"] == 2
            # a's last pin dropped: the bound re-asserts immediately
            # (b is still held, so a is the only candidate)
            assert sess.pager.is_paged(scoped("t", "a"))
            assert sess.pager.is_resident(scoped("t", "b"))
        assert sess.pager.health_doc()["resident"] == 1
    finally:
        sess.close()


def test_warm_boot_adopts_index_and_drops_it_on_page_in(tmp_path):
    store = str(tmp_path / "store")
    ka = _kernel(seed=61)
    x = np.linspace(-1.0, 1.0, 8)
    s1 = _session(tmp_path, resident_max=1, page_dir=store)
    try:
        s1.register_for("t", "a", ka, warmup=False)
        s1.register_for("t", "b", _kernel(seed=62), warmup=False)
        assert s1.pager.is_paged(scoped("t", "a"))
    finally:
        s1.close()
    # a fresh worker on the shared store boots warm: the index entry
    # pages a in off disk, bitwise-equal to the original weights
    s2 = _session(tmp_path, resident_max=1, page_dir=store)
    try:
        assert s2.pager.is_paged(scoped("t", "a"))
        out = np.asarray(s2.infer_for("t", "a", x))
        assert np.array_equal(out, _direct_ann(ka, x)[0])
        assert s2.registry.get(scoped("t", "a")).version == 0
    finally:
        s2.close()
    # the page-in dropped the index entry (it now mirrors nothing
    # paged out), so a third boot must NOT adopt stale weights
    s3 = _session(tmp_path, resident_max=1, page_dir=store)
    try:
        assert not s3.pager.is_paged(scoped("t", "a"))
        with pytest.raises(KeyError):
            s3.infer_for("t", "a", x)
    finally:
        s3.close()


def test_gc_objects_sweeps_stranded_weights(tmp_path):
    store = str(tmp_path / "store")
    sess = _session(tmp_path, resident_max=1, page_dir=store)
    try:
        name = scoped("t", "a")
        sess.register_for("t", "a", _kernel(seed=71), warmup=False)
        sess.register_for("t", "b", _kernel(seed=72), warmup=False)
        assert sess.pager.is_paged(name)
        # promotion pages a in (dropping its index) and strands a's
        # old weights object; b gets paged out in its stead
        sess.install_kernel(name, _kernel(seed=73), warmup=False)

        def objects():
            found = []
            for sub, _dirs, files in os.walk(
                    os.path.join(store, "objects")):
                found += [os.path.join(sub, f) for f in files]
            return sorted(found)

        before = objects()
        assert len(before) == 2       # a's stale v0 + b's live object
        removed, freed = sess.pager.gc_objects()
        assert removed == 1 and freed > 0
        after = objects()
        assert len(after) == 1 and set(after) <= set(before)
        # the survivor is still pageable: b comes back bitwise-clean
        x = np.linspace(-1.0, 1.0, 8)
        out = np.asarray(sess.infer_for("t", "b", x))
        assert np.array_equal(out, _direct_ann(_kernel(seed=72), x)[0])
    finally:
        sess.close()


# ------------------------------------------------------------ HTTP edge
def test_http_x_tenant_routing_quota_429_and_tenantz(tmp_path):
    k = _kernel(seed=81)
    sess = _session(
        tmp_path, fleet=True,
        tenants={"acme": TenantSpec("acme", "gold"),
                 "hog": TenantSpec("hog", "bronze", rate_rps=0.5,
                                   burst_s=0.1)})
    server = make_server(sess)
    host, port = server.server_address[:2]
    threading.Thread(target=server.serve_forever, daemon=True).start()
    try:
        sess.register_for("acme", "k", k, warmup=False)
        sess.register_for("hog", "k", k, warmup=False)
        cn = http.client.HTTPConnection(host, port, timeout=30)
        x = np.linspace(-1.0, 1.0, 8)
        body = json.dumps({"kernel": "k", "inputs": x.tolist()})

        def infer(tenant):
            hdrs = {"Content-Type": "application/json"}
            if tenant:
                hdrs["X-Tenant"] = tenant
            cn.request("POST", "/v1/infer", body=body, headers=hdrs)
            resp = cn.getresponse()
            return resp, json.loads(resp.read())

        resp, out = infer("acme")
        assert resp.status == 200
        assert np.array_equal(np.asarray(out["outputs"]),
                              _direct_ann(k, x)[0])
        # no header -> the default tenant, which owns no kernels
        resp, out = infer(None)
        assert resp.status == 404
        # hog's bucket holds exactly one token: the second request
        # inside the same instant is refused, naming the tenant
        resp, _out = infer("hog")
        assert resp.status == 200
        resp, out = infer("hog")
        assert resp.status == 429
        assert out["reason"] == "quota" and out["tenant"] == "hog"
        assert out["retriable"] is True
        assert resp.getheader("Retry-After") is not None

        cn.request("GET", "/tenantz")
        resp = cn.getresponse()
        doc = json.loads(resp.read())
        assert resp.status == 200
        assert set(doc) == {"tenants", "pager", "registry"}
        assert doc["tenants"]["acme"]["slo_class"] == "gold"
        assert doc["tenants"]["hog"]["shed_rate"] > 0
        assert doc["registry"]["count"] == 2
        cn.close()
    finally:
        server.shutdown()
        server.server_close()
        sess.close()


def test_tenantz_is_404_on_a_plain_session():
    sess = serve.Session(max_batch=8, n_buckets=2)
    server = make_server(sess)
    host, port = server.server_address[:2]
    threading.Thread(target=server.serve_forever, daemon=True).start()
    try:
        cn = http.client.HTTPConnection(host, port, timeout=10)
        cn.request("GET", "/tenantz")
        assert cn.getresponse().status == 404
        cn.close()
    finally:
        server.shutdown()
        server.server_close()
        sess.close()


def test_health_summarizes_past_health_list_max(tmp_path):
    sess = _session(tmp_path, fleet=True)
    try:
        limit = serve.Session.HEALTH_LIST_MAX
        rng = np.random.RandomState(5)
        for i in range(limit):
            k = kernel_mod.Kernel((rng.standard_normal((4, 6)),
                                   rng.standard_normal((2, 4))))
            sess.register_for(f"t{i % 4}", f"k{i}", k, warmup=False)
        doc = sess.health()
        assert isinstance(doc["kernels"], list)      # at the limit
        assert len(doc["kernels"]) == limit
        assert doc["tenancy"]["registry"]["count"] == limit

        k = kernel_mod.Kernel((rng.standard_normal((4, 6)),
                               rng.standard_normal((2, 4))))
        sess.register_for("t0", "overflow", k, warmup=False)
        doc = sess.health()
        kd = doc["kernels"]                          # one past: census
        assert isinstance(kd, dict)
        assert kd["count"] == limit + 1
        assert 0 < len(kd["sample"]) <= 16
        assert kd["shard_min"] <= kd["shard_max"]
    finally:
        sess.close()


# ------------------------------------------------------------ sink lint
def test_live_tenant_sink_lints_clean(tmp_path):
    """The real emission path must satisfy its own lint: a short run
    with paging, quota sheds, and enough outcomes to publish the p99
    gauges produces a sink ``--tenant`` accepts."""
    mod = _load_tool("check_obs_catalog")
    sink = tmp_path / "obs.jsonl"
    store = str(tmp_path / "store")
    obs.configure(str(sink))
    try:
        sess = _session(
            tmp_path, resident_max=1, page_dir=store,
            tenants={"t": TenantSpec("t", "gold"),
                     "hog": TenantSpec("hog", "bronze", rate_rps=0.5,
                                       burst_s=0.1)})
        try:
            sess.register_for("t", "a", _kernel(seed=91), warmup=False)
            sess.register_for("t", "b", _kernel(seed=92), warmup=False)
            sess.register_for("hog", "h", _kernel(seed=93),
                              warmup=False)
            x = np.linspace(-1.0, 1.0, 8)
            for i in range(10):       # past PUBLISH_EVERY: p99 lands
                sess.infer_for("t", "a" if i % 2 else "b", x)
            sess.infer_for("hog", "h", x)
            with pytest.raises(QuotaExceeded):
                sess.infer_for("hog", "h", x)
        finally:
            sess.close()
    finally:
        obs.configure(None)
    names = {r["ev"] for r in _read_sink(sink)}
    for want in ("tenant.page_out", "tenant.page_in",
                 "tenant.page_in_ms", "tenant.resident",
                 "tenant.p99_ms", "tenant.shed_rate", "tenant.shed",
                 "tenant.inflight", "tenant.close"):
        assert want in names, f"missing {want} in {sorted(names)}"
    assert mod.lint_tenant(str(sink)) == []
    assert mod.main(["--tenant", str(sink)]) == 0


def _write_sink(path, rows):
    path.write_text("".join(json.dumps(r) + "\n" for r in rows))


def _tenant_rows():
    return [
        {"ev": "tenant.page_out", "kind": "count", "n": 1,
         "kernel": "t:a", "tenant": "t"},
        {"ev": "tenant.page_in", "kind": "count", "n": 1,
         "kernel": "t:a", "tenant": "t"},
        {"ev": "tenant.page_in_ms", "kind": "hist", "value": 3.2,
         "kernel": "t:a"},
        {"ev": "tenant.resident", "kind": "gauge", "value": 2.0,
         "cap": 2, "paged": 5, "pinned": 0},
        # pins legitimately hold the set over cap: value <= cap+pinned
        {"ev": "tenant.resident", "kind": "gauge", "value": 3.0,
         "cap": 2, "paged": 4, "pinned": 1},
        {"ev": "tenant.p99_ms", "kind": "gauge", "value": 12.5,
         "tenant": "acme", "slo_class": "gold", "target_ms": 25.0},
        {"ev": "tenant.shed_rate", "kind": "gauge", "value": 0.25,
         "tenant": "hog"},
        {"ev": "serve.shed", "kind": "count", "n": 1,
         "reason": "quota", "tenant": "hog", "over": "rate"},
    ]


def test_tenant_lint_accepts_a_well_formed_sink(tmp_path):
    mod = _load_tool("check_obs_catalog")
    path = tmp_path / "tenant.jsonl"
    _write_sink(path, _tenant_rows())
    assert mod.lint_tenant(str(path)) == []


def test_tenant_lint_catches_every_schema_break(tmp_path):
    """Each clause bites: wrong kinds, anonymous paging/shed records,
    a resident gauge over cap (with and without pin slack), a bad SLO
    class, and a shed rate outside [0, 1]."""
    mod = _load_tool("check_obs_catalog")
    path = tmp_path / "tenant.jsonl"
    breaks = [
        ({"ev": "tenant.page_in", "kind": "event", "kernel": "t:a"},
         "!= 'count'"),
        ({"ev": "tenant.page_out", "kind": "count", "kernel": ""},
         "non-empty"),
        ({"ev": "tenant.page_in_ms", "kind": "gauge", "value": 3.2},
         "!= 'hist'"),
        ({"ev": "tenant.resident", "kind": "gauge", "value": -1.0,
          "cap": 2}, "finite non-negative"),
        ({"ev": "tenant.resident", "kind": "gauge", "value": 4.0,
          "cap": 2, "pinned": 1}, "exceeds"),
        ({"ev": "tenant.resident", "kind": "gauge", "value": 3.0,
          "cap": 2}, "exceeds"),
        ({"ev": "tenant.p99_ms", "kind": "gauge", "value": 9.0,
          "tenant": "t", "slo_class": "platinum"}, "slo_class"),
        ({"ev": "tenant.p99_ms", "kind": "gauge", "value": 9.0,
          "tenant": "", "slo_class": "gold"}, "non-empty"),
        ({"ev": "tenant.shed_rate", "kind": "gauge", "value": 1.5,
          "tenant": "t"}, "[0, 1]"),
        ({"ev": "tenant.shed_rate", "kind": "gauge", "value": 0.5},
         "non-empty"),
        ({"ev": "serve.shed", "kind": "count", "reason": "quota",
          "tenant": ""}, "whose budget"),
    ]
    for rec, needle in breaks:
        _write_sink(path, [rec])
        failures = mod.lint_tenant(str(path))
        assert failures, f"schema break not caught: {rec}"
        assert any(needle in f for f in failures), (needle, failures)


def test_tenant_lint_fails_a_sink_with_no_tenant_records(tmp_path):
    mod = _load_tool("check_obs_catalog")
    path = tmp_path / "quiet.jsonl"
    _write_sink(path, [{"ev": "obs.summary", "kind": "summary"}])
    assert any("no tenant records" in f
               for f in mod.lint_tenant(str(path)))
    _write_sink(path, _tenant_rows()[:1] + [
        {"ev": "tenant.resident", "kind": "gauge", "value": 9.0,
         "cap": 2, "pinned": 0}])
    assert mod.main(["--tenant", str(path)]) == 1
    assert mod.main(["--tenant"]) == 2


# ------------------------------------------------------------ loadgen
def test_loadgen_zipf_helpers_and_by_tenant_summary():
    lg = _load_tool("loadgen")
    assert lg.tenant_names(3) == ("t000", "t001", "t002")
    cdf = lg.zipf_cdf(8, 1.2)
    assert len(cdf) == 8
    assert np.all(np.diff(cdf) > 0)              # strictly increasing
    assert cdf[-1] == pytest.approx(1.0)
    rng = np.random.RandomState(3)
    draws = [lg.zipf_pick(cdf, rng) for _ in range(2000)]
    assert all(0 <= d < 8 for d in draws)
    counts = np.bincount(draws, minlength=8)
    assert counts[0] > 2 * counts[7]             # the skew is real
    with pytest.raises(ValueError):
        lg.zipf_cdf(0, 1.2)

    recs = [
        {"status": "ok", "latency_ms": 1.0, "tenant": "a"},
        {"status": "ok", "latency_ms": 2.0, "tenant": "a"},
        {"status": "shed", "latency_ms": 0.1, "tenant": "b"},
        {"status": "ok", "latency_ms": 1.5},     # untagged: no tenant
    ]
    summary = lg.summarize(recs, 1.0)
    a, b = summary["by_tenant"]["a"], summary["by_tenant"]["b"]
    assert (a["requests"], a["ok"], a["shed"]) == (2, 2, 0)
    assert (b["requests"], b["ok"], b["shed"]) == (1, 0, 1)
    # per-tenant served-latency tail: p50/p99 over ok outcomes only,
    # None for a tenant with nothing served
    assert a["p50_ms"] == pytest.approx(1.5)
    assert a["p99_ms"] == pytest.approx(1.99)
    assert b["p50_ms"] is None and b["p99_ms"] is None
    # an untenanted run keeps the old summary shape exactly
    assert "by_tenant" not in lg.summarize(recs[3:], 1.0)
