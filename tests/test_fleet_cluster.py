"""Cross-host serving fleet (hpnn_tpu/fleet/, docs/serving.md
"Cross-host fleet").

Acceptance bar (ISSUE 13): a ``ClusterRouter`` over N worker
processes answers **bitwise-identically** to a direct ``models.run``;
a checkpoint publish + fenced ``/v1/reload`` fan-out mid-traffic is
seen by every request as bitwise old-version or new-version, never a
torn mix — across ≥2 OS processes; dead workers are routed around,
reaped, and replaced; the autoscaler decision core is a pure function
with hysteresis / cool-downs / clamps / burn-dominates-queue ordering;
compiled-mode replicas pin weights to their own device on the 8-device
mesh; and the new ``fleet.*``/``cluster.*`` records pass the
``tools/check_obs_catalog.py --cluster`` schema lint.
"""

import importlib.util
import json
import os
import threading
import time
import types

import numpy as np
import pytest

from hpnn_tpu import obs, serve
from hpnn_tpu.fleet import (Autoscaler, ClusterRouter, Policy,
                            WorkerHandle, WorkerSupervisor, decide)
from hpnn_tpu.fleet.router import CheckpointPublisher
from hpnn_tpu.models import ann, kernel as kernel_mod
from hpnn_tpu.serve.batcher import Shed

ROOT = os.path.join(os.path.dirname(__file__), "..")

CONF = ("[name] drill\n[type] ANN\n[init] generate\n[seed] 7\n"
        "[input] 8\n[hidden] 5\n[output] 2\n[train] BP\n")


def _kernel(seed=7, n_in=8, hiddens=(5,), n_out=2):
    k, _ = kernel_mod.generate(seed, n_in, list(hiddens), n_out)
    return k


def _read_sink(path):
    with open(path) as fp:
        return [json.loads(ln) for ln in fp if ln.strip()]


def _ref(k, X):
    X = np.atleast_2d(np.asarray(X))
    return np.stack([np.asarray(ann.run(k.weights, x)) for x in X])


def _load_catalog_tool():
    spec = importlib.util.spec_from_file_location(
        "check_obs_catalog",
        os.path.join(ROOT, "tools", "check_obs_catalog.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


# ================================================== pure decision core
def _p(**kw):
    base = dict(min_width=1, max_width=4, up_outstanding=8.0,
                down_outstanding=1.0, up_burn=1.0, down_burn=0.5,
                up_step=2, down_step=1, up_cooldown_s=3.0,
                down_cooldown_s=15.0, down_for_s=5.0)
    base.update(kw)
    return Policy(**base)


def test_decide_scales_up_fast_on_queue_depth():
    w, reason = decide([(10.0, 12.0, 0.0, None)], width=1,
                       policy=_p(), now=10.0)
    assert (w, reason) == (3, "queue")     # up_step=2, one hot sample


def test_decide_burn_dominates_queue_depth():
    # burn hot over an EMPTY queue still scales up, and when both are
    # hot the reason is the burn rate — latency IS the objective,
    # queue depth is only its proxy
    w, reason = decide([(0.0, 0.0, 0.0, 2.0)], width=1,
                       policy=_p(), now=0.0)
    assert (w, reason) == (3, "burn")
    _w, reason = decide([(0.0, 50.0, 0.0, 2.0)], width=1,
                        policy=_p(), now=0.0)
    assert reason == "burn"


def test_decide_shed_triggers_up():
    samples = [(0.0, 0.5, 0.0, None), (1.0, 0.5, 3.0, None)]
    w, reason = decide(samples, width=2, policy=_p(), now=1.0)
    assert (w, reason) == (4, "shed")


def test_decide_up_clamps_at_max_width():
    w, reason = decide([(0.0, 99.0, 0.0, None)], width=3,
                       policy=_p(max_width=4), now=0.0)
    assert (w, reason) == (4, "queue")     # step 2 clamped to max
    w, reason = decide([(0.0, 99.0, 0.0, None)], width=4,
                       policy=_p(max_width=4), now=0.0)
    assert (w, reason) == (4, "queue_at_max")


def test_decide_up_cooldown_blocks_thrash():
    w, reason = decide([(10.0, 99.0, 0.0, None)], width=2,
                       policy=_p(up_cooldown_s=3.0), now=10.0,
                       last_up_t=8.5)
    assert (w, reason) == (2, "queue_cooldown")
    w, _ = decide([(12.0, 99.0, 0.0, None)], width=2,
                  policy=_p(up_cooldown_s=3.0), now=12.0, last_up_t=8.5)
    assert w == 4


def test_decide_down_requires_sustained_calm():
    pol = _p(down_for_s=5.0, down_cooldown_s=0.1)
    calm = [(t, 0.2, 0.0, None) for t in range(0, 11)]
    # window not yet covered: the oldest sample is too recent
    w, reason = decide(calm[-3:], width=3, policy=pol, now=10.0)
    assert (w, reason) == (3, "calm_unproven")
    # fully covered calm window: shrink by down_step=1 only
    w, reason = decide(calm, width=3, policy=pol, now=10.0)
    assert (w, reason) == (2, "calm")
    # a shed inside the window is an UP trigger, not merely a down-veto
    dirty = calm[:-2] + [(9.0, 0.2, 1.0, None), (10.0, 0.2, 0.0, None)]
    w, reason = decide(dirty, width=3, policy=pol, now=10.0)
    assert (w, reason) == (4, "shed")
    # merely-busy (not hot, not calm) really is steady state
    busy = [(float(t), 4.0, 0.0, None) for t in range(0, 11)]
    w, reason = decide(busy, width=3, policy=pol, now=10.0)
    assert (w, reason) == (3, "steady")


def test_decide_down_cooldown_and_min_clamp():
    pol = _p(down_for_s=2.0, down_cooldown_s=15.0)
    calm = [(float(t), 0.0, 0.0, None) for t in range(0, 11)]
    w, reason = decide(calm, width=2, policy=pol, now=10.0,
                       last_down_t=5.0)
    assert (w, reason) == (2, "down_cooldown")
    # an up action also arms the down cool-down (no flap after grow)
    w, reason = decide(calm, width=2, policy=pol, now=10.0,
                       last_up_t=5.0)
    assert (w, reason) == (2, "down_cooldown")
    # at min width calm is just steady state
    w, reason = decide(calm, width=1, policy=pol, now=10.0)
    assert (w, reason) == (1, "steady")


def test_decide_burn_vetoes_scale_down():
    pol = _p(down_for_s=2.0, down_cooldown_s=0.1, down_burn=0.5)
    warm = [(float(t), 0.0, 0.0, 0.8) for t in range(0, 11)]
    w, reason = decide(warm, width=3, policy=pol, now=10.0)
    assert (w, reason) == (3, "burn_veto")


def test_policy_from_env():
    env = {"HPNN_FLEET_MIN": "2", "HPNN_FLEET_MAX": "6",
           "HPNN_FLEET_UP_BURN": "1.5",
           "HPNN_FLEET_DOWN_COOLDOWN_S": "30"}
    pol = Policy.from_env(env)
    assert (pol.min_width, pol.max_width) == (2, 6)
    assert pol.up_burn == 1.5 and pol.down_cooldown_s == 30.0
    assert pol.up_step == 2                # unset knob keeps default
    assert Policy.from_env(env, max_width=9).max_width == 9
    with pytest.raises(ValueError):
        Policy.from_env({"HPNN_FLEET_MAX": "lots"})
    with pytest.raises(ValueError):        # validation still applies
        Policy.from_env({"HPNN_FLEET_MIN": "5", "HPNN_FLEET_MAX": "2"})


def test_decide_edge_inputs():
    assert decide([], width=2, policy=_p(), now=0.0) == (2, "no_data")
    assert decide([(0.0, 0.0, 0.0, None)], width=0, policy=_p(),
                  now=0.0) == (1, "below_min")
    # dict samples are accepted too (the control loop's shape)
    w, reason = decide(
        [{"t": 0.0, "outstanding": 99.0, "shed": 0, "burn": None}],
        width=1, policy=_p(), now=0.0)
    assert w == 3


def _ramp(slope, n=4, t0=0.0, base=0.5):
    """n samples climbing ``slope`` rows/worker/s, all below the
    up_outstanding level threshold so only the slope trigger can
    fire."""
    return [(t0 + t, base + slope * t, 0.0, None) for t in range(n)]


def test_decide_slope_fires_below_level_thresholds():
    pol = _p(up_slope=1.0, slope_for_s=3.0)
    # 2 rows/worker/s over 3 s: max outstanding 6.5 < up_outstanding=8
    w, reason = decide(_ramp(2.0), width=1, policy=pol, now=3.0)
    assert (w, reason) == (3, "slope")


def test_decide_slope_disabled_by_default():
    # default up_slope=0: the same ramp is steady state
    w, reason = decide(_ramp(2.0), width=1, policy=_p(), now=3.0)
    assert (w, reason) == (1, "steady")


def test_decide_slope_needs_enough_covered_window():
    pol = _p(up_slope=1.0, slope_for_s=3.0)
    # two points can't prove a ramp, whatever their slope
    w, reason = decide(_ramp(2.0, n=2, t0=2.0), width=1, policy=pol,
                       now=3.0)
    assert (w, reason) == (1, "steady")
    # three points spanning under half the window prove nothing either
    narrow = [(2.4, 0.5, 0.0, None), (2.7, 1.1, 0.0, None),
              (3.0, 1.7, 0.0, None)]
    assert decide(narrow, width=1, policy=pol,
                  now=3.0) == (1, "steady")
    # a sub-threshold ramp stays steady
    w, reason = decide(_ramp(0.4), width=1, policy=pol, now=3.0)
    assert (w, reason) == (1, "steady")


def test_decide_slope_loses_to_level_triggers():
    pol = _p(up_slope=0.1, slope_for_s=3.0)
    # queue over the level threshold names the level, not the ramp
    hot = [(t, 9.0 + t, 0.0, None) for t in range(4)]
    _w, reason = decide(hot, width=1, policy=pol, now=3.0)
    assert reason == "queue"
    # burn still dominates everything
    burning = [(t, 0.5 + 2.0 * t, 0.0, 2.0) for t in range(4)]
    _w, reason = decide(burning, width=1, policy=pol, now=3.0)
    assert reason == "burn"


def test_slope_policy_from_env():
    pol = Policy.from_env({"HPNN_FLEET_UP_SLOPE": "1.5",
                           "HPNN_FLEET_SLOPE_FOR_S": "4"})
    assert pol.up_slope == 1.5 and pol.slope_for_s == 4.0
    assert Policy.from_env({}).up_slope == 0.0   # off by default
    with pytest.raises(ValueError):
        Policy.from_env({"HPNN_FLEET_UP_SLOPE": "-1"})
    with pytest.raises(ValueError):
        Policy.from_env({"HPNN_FLEET_SLOPE_FOR_S": "0"})


# ============================================= control loop (no procs)
class _FakeSupervisor:
    def __init__(self):
        self._ranks = [0]
        self._next = 1
        self.spawned = 0
        self.drained: list = []

    def replace_dead(self):
        return []

    def width(self):
        return len(self._ranks)

    def ranks(self):
        return sorted(self._ranks)

    def spawn(self):
        self._ranks.append(self._next)
        self._next += 1
        self.spawned += 1

    def drain_and_kill(self, rank, **kw):
        self._ranks.remove(rank)
        self.drained.append(rank)


def test_autoscaler_loop_scales_up_then_down(tmp_path):
    sup = _FakeSupervisor()
    clock_now = [0.0]
    signal_now = [(20.0, 0.0, None)]       # (outstanding, shed, burn)
    scaler = Autoscaler(
        sup, router=None,
        policy=_p(max_width=3, up_step=2, up_cooldown_s=1.0,
                  down_for_s=3.0, down_cooldown_s=5.0),
        signals=lambda: signal_now[0], clock=lambda: clock_now[0])
    sink = tmp_path / "scale.jsonl"
    obs.configure(str(sink))
    try:
        width, reason = scaler.tick()
        assert (width, reason) == (3, "queue")
        assert sup.spawned == 2
        signal_now[0] = (0.0, 0.0, None)   # load gone
        for t in range(1, 12):
            clock_now[0] = float(t)
            scaler.tick()
        assert sup.width() == 1            # back down, one step at a time
        assert sup.drained == [2, 1]       # highest rank drains first
    finally:
        obs.configure(None)
    recs = _read_sink(sink)
    ups = [r for r in recs if r["ev"] == "fleet.scale_up"]
    downs = [r for r in recs if r["ev"] == "fleet.scale_down"]
    assert len(ups) == 1 and ups[0]["from_width"] == 1 \
        and ups[0]["to_width"] == 3 and ups[0]["reason"] == "queue"
    assert len(downs) == 2
    assert [d["to_width"] for d in downs] == [2, 1]
    # the recorded window passes the --cluster schema lint
    tool = _load_catalog_tool()
    assert tool.lint_cluster(str(sink)) == []


def test_autoscaler_request_up_down_external_pushes(tmp_path):
    """The tune plane's surface: request_up grows one policy step
    (arming the up-cooldown so the loop can't double-fire),
    request_down shrinks back draining highest ranks first, and both
    emit lint-clean fleet.scale_* records with the caller's reason."""
    sup = _FakeSupervisor()
    clock_now = [10.0]
    scaler = Autoscaler(sup, router=None,
                        policy=_p(max_width=3, up_step=1,
                                  up_cooldown_s=5.0),
                        signals=lambda: (20.0, 0.0, None),
                        clock=lambda: clock_now[0])
    sink = tmp_path / "push.jsonl"
    obs.configure(str(sink))
    try:
        assert scaler.request_up(reason="tune:queue") == (1, 2)
        assert sup.width() == 2
        # the push armed the up-cooldown — the hot loop can't pile on
        assert scaler.tick()[1] == "queue_cooldown"
        assert scaler.request_up(reason="tune:queue") == (2, 3)
        # clamped at max: no change, no event
        assert scaler.request_up(reason="tune:queue") is None
        assert scaler.request_down(1, reason="tune:rollback") == (3, 1)
        assert sup.width() == 1 and sup.drained == [2, 1]
        assert scaler.request_down(1, reason="tune:rollback") is None
    finally:
        obs.configure(None)
    recs = _read_sink(sink)
    ups = [r for r in recs if r["ev"] == "fleet.scale_up"]
    downs = [r for r in recs if r["ev"] == "fleet.scale_down"]
    assert [(u["from_width"], u["to_width"], u["reason"])
            for u in ups] == [(1, 2, "tune:queue"),
                              (2, 3, "tune:queue")]
    assert [(d["from_width"], d["to_width"], d["reason"])
            for d in downs] == [(3, 1, "tune:rollback")]
    tool = _load_catalog_tool()
    assert tool.lint_cluster(str(sink)) == []


# ===================================== in-process fleet (HTTP workers)
def _start_inproc_fleet(tmp_path, n=2, seed=7):
    """N real make_server workers in this process (threads, real HTTP)
    — the fast substrate for router semantics; OS-process workers are
    exercised by the supervisor fixture below."""
    from hpnn_tpu.fileio import checkpoint as ckpt_mod

    k = _kernel(seed=seed)
    path = os.path.join(str(tmp_path), "fleet.ckpt")
    ckpt_mod.dump_checkpoint(path, "V", k.weights, version=1, meta={})
    sessions, servers, handles = [], [], []
    for i in range(n):
        s = serve.Session(max_batch=16, max_wait_ms=0.5)
        s.load_kernel("V", path)
        srv = serve.make_server(s)
        threading.Thread(target=srv.serve_forever, daemon=True).start()
        sessions.append(s)
        servers.append(srv)
        handles.append(WorkerHandle(i, "127.0.0.1",
                                    srv.server_address[1]))
    pub = CheckpointPublisher({"V": path}, versions={"V": 1})
    router = ClusterRouter(workers=handles, publisher=pub)
    ns = types.SimpleNamespace(router=router, handles=handles,
                               servers=servers, sessions=sessions,
                               publisher=pub, ckpt_path=path, k=k)

    def close():
        router.close()
        for srv in servers:
            srv.shutdown()
            srv.server_close()
        for s in sessions:
            s.close()

    ns.close = close
    return ns


def test_cluster_round_trip_bitwise(tmp_path):
    fl = _start_inproc_fleet(tmp_path)
    try:
        rng = np.random.RandomState(3)
        vec = rng.uniform(-1, 1, 8)
        out = fl.router.infer("V", vec)
        assert out.shape == (2,)
        assert np.array_equal(out, np.asarray(ann.run(fl.k.weights,
                                                      vec)))
        for rows in (1, 3, 8):
            X = rng.uniform(-1, 1, (rows, 8))
            assert np.array_equal(fl.router.infer("V", X),
                                  _ref(fl.k, X))
        with pytest.raises(KeyError):
            fl.router.infer("nope", vec)
        # serve-only workers: the fleet's ingest hook answers 404-shaped
        with pytest.raises(KeyError):
            fl.router.ingest_hook("V", np.zeros((2, 8)),
                                  np.zeros((2, 2)))
    finally:
        fl.close()


def test_cluster_is_session_shaped(tmp_path):
    fl = _start_inproc_fleet(tmp_path)
    try:
        assert fl.router.kernels() == ["V"]
        assert fl.router.is_ready()
        doc = fl.router.health()
        assert doc["ready"] is True and doc["status"] == "ok"
        assert doc["cluster"]["n_workers"] == 2
        assert set(doc["workers"]) == {"w0", "w1"}
        for wdoc in doc["workers"].values():
            assert wdoc["ready"] is True
            assert wdoc["outstanding"] == 0
        assert all(name.startswith(("w0/", "w1/"))
                   for name in doc["batchers"])
        rdoc = fl.router.ready_doc()
        assert rdoc["ready"] is True and set(rdoc["workers"]) == \
            {"w0", "w1"}
        # the make_server edge composes over the cluster surface
        edge = serve.make_server(fl.router)
        threading.Thread(target=edge.serve_forever,
                         daemon=True).start()
        try:
            import http.client

            conn = http.client.HTTPConnection(
                "127.0.0.1", edge.server_address[1], timeout=5)
            conn.request("POST", "/v1/infer", json.dumps(
                {"kernel": "V", "inputs": [0.0] * 8}),
                {"Content-Type": "application/json"})
            resp = conn.getresponse()
            body = json.loads(resp.read())
            assert resp.status == 200
            assert np.array_equal(
                np.asarray(body["outputs"]),
                np.asarray(ann.run(fl.k.weights, np.zeros(8))))
            conn.close()
        finally:
            edge.shutdown()
            edge.server_close()
    finally:
        fl.close()


def test_cluster_routes_around_dead_worker(tmp_path):
    fl = _start_inproc_fleet(tmp_path)
    sink = str(tmp_path / "route.jsonl")
    try:
        # kill worker 0's HTTP front end: connection refused from now on
        fl.servers[0].shutdown()
        fl.servers[0].server_close()
        fl.sessions[0].close()
        obs.configure(sink)
        try:
            out = fl.router.infer("V", np.zeros(8))
        finally:
            obs.configure(None)
        assert np.array_equal(out, np.asarray(ann.run(fl.k.weights,
                                                      np.zeros(8))))
        recs = _read_sink(sink)
        gone = [r for r in recs if r["ev"] == "cluster.shed_around"]
        assert gone and gone[0]["rank"] == 0 \
            and gone[0]["reason"] == "gone"
        # worker 0 is cooling now: the next request skips it entirely
        obs.configure(sink)
        try:
            fl.router.infer("V", np.zeros(8))
        finally:
            obs.configure(None)
        routes = [r for r in _read_sink(sink)
                  if r["ev"] == "cluster.route"]
        assert routes[-1]["rank"] == 1
    finally:
        fl.close()


def test_cluster_all_dead_raises_shed(tmp_path):
    fl = _start_inproc_fleet(tmp_path)
    try:
        for srv in fl.servers:
            srv.shutdown()
            srv.server_close()
        for s in fl.sessions:
            s.close()
        with pytest.raises((Shed, RuntimeError)):
            fl.router.infer("V", np.zeros(8))
        router_empty = ClusterRouter(workers=[])
        with pytest.raises(Shed) as exc:
            router_empty.infer("V", np.zeros(8))
        assert exc.value.reason == "no_worker"
    finally:
        fl.close()


def test_cluster_install_fence_old_or_new_inproc(tmp_path):
    """The PR 10 torn-read test over HTTP workers: concurrent infers
    during churning installs answer bitwise old-or-new, never a mix
    (the cross-process acceptance twin runs under the supervisor
    fixture below)."""
    fl = _start_inproc_fleet(tmp_path)
    sink = str(tmp_path / "fence.jsonl")
    try:
        X = np.linspace(-1.0, 1.0, 24).reshape(3, 8)
        k_versions = [fl.k] + [_kernel(seed=s) for s in (11, 13, 17)]
        allowed = [_ref(k, X) for k in k_versions]
        stop = threading.Event()
        torn: list = []

        def infer_loop():
            while not stop.is_set():
                out = np.asarray(fl.router.infer("V", X,
                                                 timeout_s=10.0))
                if not any(np.array_equal(out, a) for a in allowed):
                    torn.append(out)
                    return

        threads = [threading.Thread(target=infer_loop)
                   for _ in range(4)]
        # the sink stays armed over the whole threaded window:
        # reconfiguring mid-flight would close the file under the
        # emitting infer threads
        obs.configure(sink)
        try:
            for t in threads:
                t.start()
            for k_new in k_versions[1:]:
                time.sleep(0.05)
                fl.router.install_kernel("V", k_new)
            time.sleep(0.1)
        finally:
            stop.set()
            for t in threads:
                t.join(timeout=30)
            obs.configure(None)
        assert not torn, "torn read: an answer matched no version"
        # converged on the final version, on every worker
        final = allowed[-1]
        assert np.array_equal(fl.router.infer("V", X), final)
        for h in fl.handles:
            assert np.array_equal(h.infer("V", X), final)
        fences = [r for r in _read_sink(sink)
                  if r["ev"] == "cluster.fence"]
        assert len(fences) == 3
        assert all(f["op"] == "install" and f["workers"] == 2
                   for f in fences)
    finally:
        fl.close()


# =============================================== --cluster schema lint
def _write_jsonl(path, recs):
    with open(path, "w") as fp:
        for r in recs:
            fp.write(json.dumps(r) + "\n")


def test_cluster_lint_accepts_fleet_lifecycle(tmp_path):
    tool = _load_catalog_tool()
    path = str(tmp_path / "good.jsonl")
    _write_jsonl(path, [
        {"ev": "fleet.worker_up", "kind": "event", "rank": 0,
         "port": 8701, "pid": 41, "kind_w": "serve", "spawn_s": 2.5},
        {"ev": "fleet.width", "kind": "gauge", "value": 1.0},
        {"ev": "cluster.route", "kind": "count", "rank": 0,
         "kernel": "V", "rows": 3, "n": 1},
        {"ev": "cluster.outstanding", "kind": "gauge", "rank": 0,
         "value": 3.0},
        {"ev": "fleet.scale_up", "kind": "event", "from_width": 1,
         "to_width": 3, "reason": "burn", "burn": 2.0},
        {"ev": "fleet.worker_up", "kind": "event", "rank": 1,
         "port": 8702, "pid": 42, "spawn_s": 0.5},
        {"ev": "fleet.worker_up", "kind": "event", "rank": 2,
         "port": 8703, "pid": 43, "spawn_s": 0.4},
        {"ev": "cluster.shed_around", "kind": "count", "rank": 1,
         "kernel": "V", "reason": "queue_full", "n": 1},
        {"ev": "cluster.fence", "kind": "event", "op": "install",
         "kernel": "V", "from_version": 1, "to_version": 2,
         "workers": 3},
        {"ev": "fleet.scale_down", "kind": "event", "from_width": 3,
         "to_width": 2, "reason": "calm"},
        {"ev": "fleet.worker_down", "kind": "event", "rank": 2,
         "pid": 43, "reason": "scale_down", "returncode": 0,
         "escalated": False, "alive_s": 9.0},
    ])
    assert tool.lint_cluster(path) == []


def test_cluster_lint_bites_on_bad_records(tmp_path):
    tool = _load_catalog_tool()
    path = str(tmp_path / "bad.jsonl")
    _write_jsonl(path, [
        # spawn without its latency field
        {"ev": "fleet.worker_up", "kind": "event", "rank": 0,
         "port": 8701, "pid": 41},
        # rank admitted twice, never reused
        {"ev": "fleet.worker_up", "kind": "event", "rank": 0,
         "port": 8702, "pid": 42, "spawn_s": 1.0},
        # down for a rank never admitted
        {"ev": "fleet.worker_down", "kind": "event", "rank": 7,
         "pid": 9, "reason": "crash", "alive_s": 1.0},
        # a "scale up" that shrinks, an infinite width
        {"ev": "fleet.scale_up", "kind": "event", "from_width": 3,
         "to_width": 2, "reason": "burn"},
        {"ev": "fleet.scale_down", "kind": "event",
         "from_width": float("inf"), "to_width": 1, "reason": "calm"},
        # an empty-fleet gauge
        {"ev": "fleet.width", "kind": "gauge", "value": 0.0},
        # a route-around that can't say why
        {"ev": "cluster.shed_around", "kind": "count", "rank": 0,
         "n": 1},
    ])
    failures = "\n".join(tool.lint_cluster(path))
    assert "spawn_s" in failures
    assert "admitted twice" in failures
    assert "never admitted" in failures
    assert "not a scale-up" in failures
    assert "ints >= 1" in failures
    assert "fleet.width" in failures
    assert "reason" in failures
    # and an empty file fails: the lint must not pass vacuously
    empty = str(tmp_path / "empty.jsonl")
    _write_jsonl(empty, [{"ev": "serve.listen", "kind": "event"}])
    assert tool.lint_cluster(empty)


def test_drill_catalog_knows_worker_drill(tmp_path):
    tool = _load_catalog_tool()
    assert "drill.worker" in tool.DRILL_EVS
    path = str(tmp_path / "drill.jsonl")
    # a passing worker drill without the replacement proof must bite
    _write_jsonl(path, [
        {"ev": "drill.worker", "ok": True, "survivors_lost": 0,
         "survivor_bitwise": True, "recovery_s": 0.5, "lost": 0,
         "requests": 100},
    ])
    failures = "\n".join(tool.lint_chaos(path))
    assert "replaced_s" in failures
    _write_jsonl(path, [
        {"ev": "drill.worker", "ok": True, "survivors_lost": 0,
         "survivor_bitwise": True, "recovery_s": 0.5,
         "replaced_s": 4.2, "lost": 0, "requests": 100},
    ])
    assert tool.lint_chaos(path) == []


# =========================================== compiled-mode device pins
def test_replica_device_pinning_on_8_device_mesh():
    """Satellite: each compiled-mode Replica's weights live on its OWN
    device (committed buffers checked via .devices()) — the multi-chip
    placement claim, measured on the 8-virtual-device CPU mesh the
    suite forces (tests/conftest.py)."""
    import jax

    local = jax.local_devices()
    assert len(local) == 8                 # the conftest mesh contract
    router = serve.Router(4, mode="compiled", max_batch=8, n_buckets=2,
                          max_wait_ms=0.5)
    try:
        router.register_kernel("V", _kernel(), warmup=True)
        seen_devices = []
        for rep in router.replicas:
            dev = local[rep.rank % len(local)]
            assert rep.engine.device_index == rep.rank
            entry = rep.registry.get("V")
            weights = rep.engine._device_weights(entry)
            for a in weights:
                assert a.devices() == {dev}, (
                    f"replica r{rep.rank} weights on {a.devices()}, "
                    f"want {dev}")
            assert rep.engine.compiled_count() >= 1
            # the executable's committed output lands on the pin too
            fn = rep.engine._compiled_forward(
                entry, rep.engine.buckets[0], np.float64)
            out = fn(np.zeros((rep.engine.buckets[0], 8)))
            assert getattr(out, "devices", lambda: {dev})() == {dev}
            seen_devices.append(dev)
        assert len(set(seen_devices)) == 4   # four replicas, four chips
        # and the routed answer is still correct end to end
        out = np.asarray(router.infer("V", np.zeros((3, 8))))
        assert out.shape == (3, 2)
    finally:
        router.close()


# ========================================= OS-process fleet (accept.)
@pytest.fixture(scope="module")
def proc_fleet(tmp_path_factory):
    """Two REAL online_nn worker processes under a WorkerSupervisor,
    sharing one promotion WAL (the fleet-wide reload substrate), one
    compile cache, a live in-process collector, and {rank}-expanded
    metrics sinks — the cross-host acceptance substrate."""
    from hpnn_tpu.obs import collector as collector_mod
    from hpnn_tpu.online import wal as wal_mod

    workdir = str(tmp_path_factory.mktemp("proc_fleet"))
    conf_path = os.path.join(workdir, "nn.conf")
    with open(conf_path, "w") as fp:
        fp.write(CONF)
    wal_dir = os.path.join(workdir, "wal")
    k_seed = _kernel(seed=11)
    wal = wal_mod.PromotionWAL(wal_dir)
    rec = wal.commit("drill", k_seed.weights, version=1, reason="seed")
    ckpt_path = os.path.join(wal_dir, rec["ckpt"])
    del wal  # the publisher owns WAL writes from here on

    coll_srv = collector_mod.start_collector()
    coll_port = coll_srv.server_address[1]

    spawn_sink = os.path.join(workdir, "supervisor.jsonl")
    obs.configure(spawn_sink)
    sup = WorkerSupervisor(
        conf_path, workdir=workdir, kind="online", wal_dir=wal_dir,
        collector=f"http://127.0.0.1:{coll_port}",
        args=("--interval-s", "600"),      # trainer parked: reload is
                                           # the only promotion path
        env={"JAX_PLATFORMS": "cpu",
             "HPNN_COLLECTOR_FLUSH_S": "0.1",
             "HPNN_METRICS": os.path.join(workdir, "w{rank}.jsonl")})
    try:
        sup.spawn()
        sup.spawn()
    finally:
        obs.configure(None)
    pub = CheckpointPublisher(wal_dir=wal_dir)
    router = ClusterRouter(supervisor=sup, publisher=pub)
    ns = types.SimpleNamespace(
        supervisor=sup, router=router, publisher=pub,
        ckpt_path=ckpt_path, workdir=workdir, k_seed=k_seed,
        spawn_sink=spawn_sink, collector=coll_srv)
    yield ns
    router.close()
    sup.close()
    collector_mod.stop_collector(coll_srv)


def _ensure_width(fl, n=2):
    while fl.supervisor.width() < n:
        fl.supervisor.spawn()


def test_supervisor_admits_ready_workers(proc_fleet):
    fl = proc_fleet
    assert fl.supervisor.width() == 2
    handles = fl.supervisor.handles()
    assert [h.rank for h in handles] == [0, 1]
    assert all(h.ready() for h in handles)
    ups = [r for r in _read_sink(fl.spawn_sink)
           if r["ev"] == "fleet.worker_up"]
    assert {r["rank"] for r in ups} == {0, 1}
    assert len({r["port"] for r in ups}) == 2
    for r in ups:
        assert r["pid"] > 0 and r["spawn_s"] >= 0.0
        assert r["kind"] == "online"
    # the supervisor sink itself passes the --cluster schema lint
    tool = _load_catalog_tool()
    assert tool.lint_cluster(fl.spawn_sink) == []
    # {rank}-expanded per-worker sinks exist and carry records from
    # DIFFERENT pids (one obs_report --merge timeline covers the fleet)
    pids = set()
    for rank in (0, 1):
        sink = os.path.join(fl.workdir, f"w{rank}.jsonl")
        assert os.path.exists(sink), "per-worker {rank} sink missing"
        pids |= {r.get("pid") for r in _read_sink(sink)
                 if r.get("pid")}
    assert len(pids) >= 2
    # warm-boot substrate: one shared compile cache dir, injected
    assert os.path.isdir(fl.supervisor.cache_dir)


def test_cluster_round_trip_across_processes(proc_fleet):
    fl = proc_fleet
    _ensure_width(fl)
    k_known = _kernel(seed=23)
    fl.router.install_kernel("drill", k_known)
    X = np.linspace(-1.0, 1.0, 24).reshape(3, 8)
    out = np.asarray(fl.router.infer("drill", X, timeout_s=10.0))
    assert np.array_equal(out, _ref(k_known, X))
    vec = np.linspace(-0.5, 0.5, 8)
    assert np.array_equal(
        np.asarray(fl.router.infer("drill", vec, timeout_s=10.0)),
        np.asarray(ann.run(k_known.weights, vec)))


def test_fleet_promotion_fence_across_processes(proc_fleet):
    """ISSUE acceptance: concurrent infers across ≥2 worker PROCESSES
    answer bitwise old-or-new weights, never torn, during a churning
    install sequence — the cross-host analogue of the PR 10 router
    fence test."""
    fl = proc_fleet
    _ensure_width(fl)
    X = np.linspace(-1.0, 1.0, 24).reshape(3, 8)
    k_base = _kernel(seed=31)
    fl.router.install_kernel("drill", k_base)
    churn = [_kernel(seed=s) for s in (37, 41, 43)]
    allowed = [_ref(k, X) for k in [k_base] + churn]
    stop = threading.Event()
    torn: list = []
    served = [0]

    def infer_loop():
        while not stop.is_set():
            out = np.asarray(fl.router.infer("drill", X,
                                             timeout_s=10.0))
            if not any(np.array_equal(out, a) for a in allowed):
                torn.append(out)
                return
            served[0] += 1

    threads = [threading.Thread(target=infer_loop) for _ in range(4)]
    for t in threads:
        t.start()
    try:
        for k_new in churn:
            time.sleep(0.1)
            fl.router.install_kernel("drill", k_new)
        time.sleep(0.2)
    finally:
        stop.set()
        for t in threads:
            t.join(timeout=60)
    assert not torn, "torn read across worker processes"
    assert served[0] > 0
    final = allowed[-1]
    assert np.array_equal(
        np.asarray(fl.router.infer("drill", X, timeout_s=10.0)), final)
    # every worker process converged on the final weights
    for h in fl.supervisor.handles():
        assert np.array_equal(np.asarray(h.infer("drill", X,
                                                 timeout_s=10.0)),
                              final)


def test_collector_covers_whole_fleet(proc_fleet):
    fl = proc_fleet
    _ensure_width(fl)
    # drive a little traffic so both workers flush telemetry
    for _ in range(4):
        fl.router.infer("drill", np.zeros(8), timeout_s=10.0)
    deadline = time.monotonic() + 10.0
    workers = {}
    while time.monotonic() < deadline:
        workers = fl.collector.collector.fleetz().get("workers", {})
        if len(workers) >= 2:
            break
        time.sleep(0.2)
    assert len(workers) >= 2, f"collector saw only {list(workers)}"


def test_crash_is_reaped_and_replaced(proc_fleet):
    fl = proc_fleet
    _ensure_width(fl)
    sink = os.path.join(fl.workdir, "crash.jsonl")
    victim = fl.supervisor.ranks()[0]
    survivor = fl.supervisor.ranks()[1]
    sur_handle = fl.supervisor.workers[survivor].handle
    X = np.linspace(-1.0, 1.0, 8)
    before = np.asarray(sur_handle.infer("drill", X, timeout_s=10.0))
    obs.configure(sink)
    try:
        fl.supervisor.kill9(victim)
        fl.supervisor.workers[victim].proc.wait(timeout=10)
        # the router routes around the corpse without losing the request
        out = np.asarray(fl.router.infer("drill", X, timeout_s=10.0))
        assert np.array_equal(out, before)   # survivor, bitwise
        replaced = fl.supervisor.replace_dead()
        assert len(replaced) == 1
        assert fl.supervisor.width() == 2
        assert replaced[0].handle.ready()
    finally:
        obs.configure(None)
    recs = _read_sink(sink)
    downs = [r for r in recs if r["ev"] == "fleet.worker_down"]
    ups = [r for r in recs if r["ev"] == "fleet.worker_up"]
    assert downs and downs[0]["rank"] == victim \
        and downs[0]["reason"] == "crash"
    assert ups and ups[0]["rank"] == replaced[0].rank
    # the replacement answers the same weights, bitwise
    assert np.array_equal(
        np.asarray(replaced[0].handle.infer("drill", X,
                                            timeout_s=10.0)), before)


def test_drain_and_kill_sigterm_exits_clean(proc_fleet):
    fl = proc_fleet
    _ensure_width(fl)
    sink = os.path.join(fl.workdir, "drain.jsonl")
    victim = fl.supervisor.ranks()[-1]
    obs.configure(sink)
    try:
        rc = fl.supervisor.drain_and_kill(victim)
    finally:
        obs.configure(None)
    assert rc == 0           # online_nn's install_drain path: exit 0
    assert victim not in fl.supervisor.ranks()
    downs = [r for r in _read_sink(sink)
             if r["ev"] == "fleet.worker_down"]
    assert downs and downs[0]["rank"] == victim
    assert downs[0]["reason"] == "scale_down"
    assert downs[0]["escalated"] is False
    # the fleet keeps serving on the survivor
    out = fl.router.infer("drill", np.zeros(8), timeout_s=10.0)
    assert np.asarray(out).shape == (2,)
