"""End-to-end: conf → train_nn → kernel.opt → run_nn on tiny synthetic data.

This is the framework's ``make check`` analogue (SURVEY.md §4.1): the
CLIs run in-process over a small separable problem and must emit the
reference's stdout token protocol and produce a reloadable kernel.
"""

import os
import re

import numpy as np
import pytest

from hpnn_tpu.cli import run_nn, train_nn


def _write_sample(path, x, t):
    with open(path, "w") as fp:
        fp.write(f"[input] {len(x)}\n")
        fp.write(" ".join("%7.5f" % v for v in x) + "\n")
        fp.write(f"[output] {len(t)}\n")
        fp.write(" ".join("%.1f" % v for v in t) + "\n")


@pytest.fixture
def workdir(tmp_path, monkeypatch):
    rng = np.random.default_rng(42)
    samples = tmp_path / "samples"
    samples.mkdir()
    # two well-separated classes in 8-dim space
    centers = np.array([[1.0] * 4 + [-1.0] * 4, [-1.0] * 4 + [1.0] * 4])
    for i in range(20):
        c = i % 2
        x = centers[c] + 0.1 * rng.normal(size=8)
        t = np.full(2, -1.0)
        t[c] = 1.0
        _write_sample(samples / f"s{i:05d}.txt", x, t)
    monkeypatch.chdir(tmp_path)
    return tmp_path


def _conf(tmp_path, typ="ANN", train="BP", init="generate"):
    text = (
        "# test conf\n"
        "[name] E2E\n"
        f"[type] {typ}\n"
        f"[init] {init}\n"
        "[seed] 1234\n"
        "[input] 8\n"
        "[hidden] 6\n"
        "[output] 2\n"
        f"[train] {train}\n"
        "[sample_dir] ./samples\n"
        "[test_dir] ./samples\n"
    )
    p = tmp_path / "nn.conf"
    p.write_text(text)
    return str(p)


@pytest.mark.parametrize(
    "typ,train", [("ANN", "BP"), ("ANN", "BPM"), ("SNN", "BP"), ("SNN", "BPM")]
)
def test_train_and_run(workdir, capsys, typ, train):
    conf = _conf(workdir, typ=typ, train=train)
    rc = train_nn.main(["-v", "-v", "-v", conf])
    assert rc == 0
    out = capsys.readouterr().out
    assert os.path.exists("kernel.tmp")
    assert os.path.exists("kernel.opt")
    # stdout token protocol
    assert "NN: TRAINING FILE:" in out
    assert re.search(r" init= *[0-9.]+", out)
    assert re.search(r" N_ITER= *\d+", out)
    assert re.search(r" final=", out)
    if typ == "ANN" or train == "BPM":
        assert ("SUCCESS!" in out) or ("FAIL!" in out)
    else:
        # SNN BP quirk: no SUCCESS!/FAIL! token
        assert "SUCCESS!" not in out and "FAIL!" not in out

    # now evaluate with the trained kernel
    cont = workdir / "cont.conf"
    cont.write_text(
        open(conf).read().replace("[init] generate", "[init] kernel.opt")
    )
    rc = run_nn.main(["-v", "-v", str(cont)])
    assert rc == 0
    out = capsys.readouterr().out
    assert "NN: TESTING FILE:" in out
    passes = out.count("[PASS]")
    fails = len(re.findall(r"\[FAIL idx=\d+\]", out))
    assert passes + fails == 20
    # trivially separable data: the trained net must classify it
    assert passes >= 18, out


def test_train_reproducible(workdir, capsys):
    conf = _conf(workdir)
    assert train_nn.main([conf]) == 0
    k1 = open("kernel.opt").read()
    assert train_nn.main([conf]) == 0
    k2 = open("kernel.opt").read()
    assert k1 == k2


@pytest.mark.parametrize(
    "typ,train", [("ANN", "BP"), ("ANN", "BPM"), ("SNN", "BP"), ("SNN", "BPM")]
)
def test_tp_cli_matches_single_device(workdir, capsys, typ, train):
    """`--mesh 1x4` (the reference's mpirun row-split mode, ref:
    src/ann.c:912-936) must produce the SAME token stream and the same
    kernel.opt weights as the single-device per-sample driver."""
    conf = _conf(workdir, typ=typ, train=train)
    assert train_nn.main(["-v", "-v", conf]) == 0
    out_single = capsys.readouterr().out
    k_single = open("kernel.opt").read()

    assert train_nn.main(["-v", "-v", "--mesh", "1x4", conf]) == 0
    out_tp = capsys.readouterr().out
    k_tp = open("kernel.opt").read()

    assert out_tp == out_single
    w_s = _rows(k_single)
    w_t = _rows(k_tp)
    assert len(w_s) == len(w_t)
    for (_, a), (_, b) in zip(w_s, w_t):
        np.testing.assert_allclose(b, a, atol=1e-10)

    # eval parity: --mesh forward pass prints identical verdicts
    cont = workdir / "cont.conf"
    cont.write_text(
        open(conf).read().replace("[init] generate", "[init] kernel.opt")
    )
    assert run_nn.main(["-v", "-v", str(cont)]) == 0
    ev_single = capsys.readouterr().out
    assert run_nn.main(["-v", "-v", "--mesh", "1x4", str(cont)]) == 0
    ev_tp = capsys.readouterr().out
    assert ev_tp == ev_single
    assert "[PASS]" in ev_single


def _rows(kernel_text):
    """(line_no, weight_row) pairs from kernel-format text."""
    out = []
    for i, line in enumerate(kernel_text.splitlines()):
        if line and not line.startswith("["):
            out.append((i, np.fromstring(line, sep=" ")))
    return out


def test_tp_cli_rejects_data_axis(workdir, capsys):
    conf = _conf(workdir)
    assert train_nn.main(["--mesh", "2x2", conf]) == -1


def test_fused_round_stall_halves_chunk(workdir, capsys, monkeypatch):
    """A dispatch killed WITHOUT the crash handler running (the
    tutorial timeout's SIGKILL) must still shrink the chunk: each
    resume that finds zero progress since the last resume halves the
    stored hint (advisor r3)."""
    from hpnn_tpu import config
    from hpnn_tpu.train import driver, loop
    from hpnn_tpu.utils import logging as log

    log.set_verbose(2)
    conf_path = _conf(workdir)
    state = workdir / "round.state"
    monkeypatch.setenv("HPNN_FUSE_STATE", str(state))
    monkeypatch.setenv("HPNN_FUSE_CHUNK", "128")

    def killed_epoch(*a, **kw):
        # KeyboardInterrupt models SIGKILL for the checkpoint logic:
        # it propagates past the JaxRuntimeError handler unhandled
        raise KeyboardInterrupt

    real_epoch = loop.train_epoch_lax
    monkeypatch.setattr(loop, "train_epoch_lax", killed_epoch)
    expect = [128, 64, 32]  # initial save, then two stall-halvings
    for want_chunk in expect:
        conf = config.load_conf(conf_path)
        with pytest.raises(KeyboardInterrupt):
            driver.train_kernel(conf)
        z = np.load(state, allow_pickle=False)
        assert int(z["chunk"]) == want_chunk
        assert int(z["done"]) == 0
    capsys.readouterr()

    # a surviving attempt completes the round from the shrunken chunk
    monkeypatch.setattr(loop, "train_epoch_lax", real_epoch)
    monkeypatch.setenv("HPNN_FUSE_EPOCH", "0")
    monkeypatch.delenv("HPNN_FUSE_STATE")
    assert train_nn.main(["-v", "-v", "-v", conf_path]) == 0
    want = capsys.readouterr().out
    monkeypatch.setenv("HPNN_FUSE_EPOCH", "1")
    monkeypatch.setenv("HPNN_FUSE_STATE", str(state))
    conf2 = config.load_conf(conf_path)
    assert driver.train_kernel(conf2) is True
    got = capsys.readouterr().out

    def training_lines(s):
        return [ln for ln in s.splitlines() if "TRAINING FILE" in ln]

    assert training_lines(got) == training_lines(want)
    assert not state.exists()


def test_tp_fused_round_chunked_matches_unchunked(workdir, capsys,
                                                 monkeypatch):
    """TP fused rounds (scan inside the shard_map) with a small
    HPNN_FUSE_CHUNK: chunk-carried sharded weights + chunked token
    emission == the default one-chunk TP round."""
    conf = _conf(workdir)
    assert train_nn.main(["-v", "-v", "--mesh", "1x4", conf]) == 0
    want = capsys.readouterr().out
    want_kernel = open("kernel.opt").read()

    monkeypatch.setenv("HPNN_FUSE_CHUNK", "3")
    assert train_nn.main(["-v", "-v", "--mesh", "1x4", conf]) == 0
    chunked = capsys.readouterr().out
    assert chunked == want
    assert open("kernel.opt").read() == want_kernel


def test_tp_fused_crash_resume(workdir, capsys, monkeypatch):
    """A TP fused round killed mid-chunk resumes from the checkpoint
    (padded host weights re-sharded onto the mesh): concatenated token
    stream and final weights identical to an uninterrupted TP round."""
    import jax

    from hpnn_tpu import config
    from hpnn_tpu.cli import common
    from hpnn_tpu.parallel import tp
    from hpnn_tpu.train import driver

    conf_path = _conf(workdir)
    monkeypatch.setenv("HPNN_FUSE_CHUNK", "8")
    assert train_nn.main(["-v", "-v", "--mesh", "1x4", conf_path]) == 0
    want = capsys.readouterr().out
    want_kernel = open("kernel.opt").read()

    mesh = common.tp_mesh("1x4")
    state = workdir / "tp.state"
    monkeypatch.setenv("HPNN_FUSE_STATE", str(state))
    real_make = tp.make_train_epoch_fn
    calls = {"n": 0}

    def make_dying(*a, **kw):
        real = real_make(*a, **kw)

        def fn(*fa, **fkw):
            calls["n"] += 1
            if calls["n"] == 1:
                raise jax.errors.JaxRuntimeError(
                    "UNAVAILABLE: TPU worker process crashed (simulated)")
            return real(*fa, **fkw)

        return fn

    monkeypatch.setattr(tp, "make_train_epoch_fn", make_dying)
    conf = config.load_conf(conf_path)
    with pytest.raises(jax.errors.JaxRuntimeError):
        driver.train_kernel(conf, mesh=mesh)
    part1 = capsys.readouterr().out
    # handler checkpoint: zero progress, chunk kept (already below the
    # 32-sample halving floor), PADDED weights
    assert state.exists()
    z = np.load(state, allow_pickle=False)
    assert int(z["done"]) == 0
    assert int(z["chunk"]) == 8
    assert z["w0"].shape[0] % 4 == 0  # padded to the model-axis size

    conf2 = config.load_conf(conf_path)
    assert driver.train_kernel(conf2, mesh=mesh) is True
    part2 = capsys.readouterr().out

    def training_lines(s):
        return [ln for ln in s.splitlines() if "TRAINING FILE" in ln]

    assert training_lines(part1 + part2) == training_lines(want)
    assert not state.exists()
    with open("kernel.opt", "w") as fp:
        config.dump_kernel(conf2, fp)
    assert open("kernel.opt").read() == want_kernel


def test_fused_round_token_alignment_with_bad_files(workdir, capsys,
                                                    monkeypatch):
    """Fused-round edge cases: an unreadable or dimension-mismatched
    sample file must produce a header-only line with every other file's
    tokens unshifted — stream identical to HPNN_FUSE_EPOCH=0."""
    conf = _conf(workdir)
    # corrupt one file mid-shuffle: read_sample -> None
    (workdir / "samples" / "s00007.txt").write_text("garbage\n")

    def run(fuse):
        monkeypatch.setenv("HPNN_FUSE_EPOCH", fuse)
        assert train_nn.main(["-v", "-v", "-v", conf]) == 0
        return capsys.readouterr().out

    fused, streamed = run("1"), run("0")
    assert fused == streamed
    # the corrupt file's line is header-only: filename then next header
    m = re.search(r"TRAINING FILE: *s00007.txt\s*\t(NN: TRAINING|$)", fused)
    assert m, fused
    assert fused.count("N_ITER=") == 19

    # dimension mismatch: skipped with a warning in BOTH paths (the
    # reference's behavior here is out-of-bounds C reads — undefined)
    _write_sample(workdir / "samples" / "s00007.txt",
                  np.zeros(5), np.array([1.0, -1.0]))
    fused2, streamed2 = run("1"), run("0")
    assert fused2 == streamed2
    assert fused2.count("N_ITER=") == 19
    assert re.search(r"TRAINING FILE: *s00007.txt\s*\tNN: TRAINING", fused2)


def test_fused_round_chunked_matches_streaming(workdir, capsys, monkeypatch):
    """HPNN_FUSE_CHUNK smaller than the sample count: chunk-carried
    weights + chunked token emission == the streaming path."""
    conf = _conf(workdir)

    def run(env):
        for k, v in env.items():
            monkeypatch.setenv(k, v)
        assert train_nn.main(["-v", "-v", "-v", conf]) == 0
        return capsys.readouterr().out

    chunked = run({"HPNN_FUSE_EPOCH": "1", "HPNN_FUSE_CHUNK": "3"})
    streamed = run({"HPNN_FUSE_EPOCH": "0"})
    assert chunked == streamed
    assert chunked.count("N_ITER=") == 20


def test_fused_round_crash_resume(workdir, capsys, monkeypatch):
    """HPNN_FUSE_STATE: a round killed mid-chunk resumes from the
    checkpoint — concatenated token stream and final weights identical
    to an uninterrupted streaming round."""
    from hpnn_tpu import config
    from hpnn_tpu.train import driver, loop

    conf_path = _conf(workdir)
    monkeypatch.setenv("HPNN_FUSE_EPOCH", "0")
    assert train_nn.main(["-v", "-v", "-v", conf_path]) == 0
    want = capsys.readouterr().out
    want_kernel = open("kernel.opt").read()

    state = workdir / "round.state"
    monkeypatch.setenv("HPNN_FUSE_EPOCH", "1")
    monkeypatch.setenv("HPNN_FUSE_CHUNK", "128")
    monkeypatch.setenv("HPNN_FUSE_STATE", str(state))
    # crash the TPU-worker way (the real failure raises
    # jax.errors.JaxRuntimeError): die inside the FIRST chunk dispatch
    # — the only possible checkpoint writer is then the crash handler
    import jax

    real_epoch = loop.train_epoch_lax
    calls = {"n": 0}

    def dying_epoch(*a, **kw):
        calls["n"] += 1
        if calls["n"] == 1:
            raise jax.errors.JaxRuntimeError(
                "UNAVAILABLE: TPU worker process crashed (simulated)")
        return real_epoch(*a, **kw)

    monkeypatch.setattr(loop, "train_epoch_lax", dying_epoch)
    conf = config.load_conf(conf_path)
    with pytest.raises(jax.errors.JaxRuntimeError):
        driver.train_kernel(conf)
    part1 = capsys.readouterr().out
    # handler checkpoint: zero progress, chunk hint HALVED (128 → 64),
    # weights = the round's start state (host copy)
    assert state.exists()
    z = np.load(state, allow_pickle=False)
    assert int(z["done"]) == 0
    assert int(z["chunk"]) == 64

    # new "process": resume and finish the round
    monkeypatch.setattr(loop, "train_epoch_lax", real_epoch)
    conf2 = config.load_conf(conf_path)
    assert driver.train_kernel(conf2) is True
    part2 = capsys.readouterr().out

    def training_lines(s):
        return [ln for ln in s.splitlines() if "TRAINING FILE" in ln]

    # the two partial runs each re-print kernel-generation headers;
    # the round's sample token stream is the contract
    assert training_lines(part1 + part2) == training_lines(want)
    assert not state.exists()  # completed round cleans up
    with open("kernel.opt", "w") as fp:
        config.dump_kernel(conf2, fp)
    assert open("kernel.opt").read() == want_kernel


def test_checkpoint_not_adopted_by_cont_round(workdir, capsys, monkeypatch):
    """Advisor r3: with [seed] 0, a leftover crash checkpoint from a
    generate round over the same dir/topology must NOT be silently
    adopted by a later cont round ([init] kernel.opt) — the starting-
    weights identity in the key keeps them apart."""
    from hpnn_tpu import config
    from hpnn_tpu.train import driver
    from hpnn_tpu.utils import logging as log

    conf_path = _conf(workdir)
    state = workdir / "round.state"
    monkeypatch.setenv("HPNN_FUSE_STATE", str(state))
    log.set_verbose(2)
    try:
        # round 0 (generate): train fully, then forge a leftover stale
        # checkpoint by re-saving the completed round's state file
        conf0 = config.load_conf(conf_path)
        assert driver.train_kernel(conf0) is True
        with open("kernel.opt", "w") as fp:
            config.dump_kernel(conf0, fp)
        # forge: a generate-round checkpoint at done=5, garbage weights
        shapes = tuple(tuple(int(d) for d in np.asarray(w).shape)
                       for w in conf0.kernel.weights)
        key0 = driver._fuse_state_key(
            str(workdir / "samples"), "ann", False, shapes, "generate")
        driver._save_fuse_state(
            str(state), key0, conf0.seed, 5, 16,
            [np.zeros(s) for s in shapes])
        capsys.readouterr()

        # cont round with [seed] 0: must NOT adopt the generate-round
        # checkpoint — all 20 samples train (a wrongly-adopted done=5
        # checkpoint would skip five token lines with zeroed weights)
        cont = workdir / "cont.conf"
        cont.write_text(
            open(conf_path).read()
            .replace("[init] generate", "[init] kernel.opt")
            .replace("[seed] 1234", "[seed] 0")
        )
        conf1 = config.load_conf(str(cont))
        assert driver.train_kernel(conf1) is True
    finally:
        log.set_verbose(0)
    out = capsys.readouterr().out
    assert out.count("TRAINING FILE") == 20
    # the cont round ran under its OWN key (the stale checkpoint was
    # superseded, never adopted) and cleaned up after completing
    assert not state.exists()


def test_batch_checkpoint_key_binds_hyperparams(tmp_path, capsys,
                                                monkeypatch):
    """A batch checkpoint from a different batch size must not be
    adopted (the key binds B/lr/epochs)."""
    from hpnn_tpu.train import batch as batch_mod_local

    import tests.test_batch as tb
    from hpnn_tpu.utils import logging as log

    conf = tb._conf(tmp_path)
    state = tmp_path / "batch.state"
    monkeypatch.setenv("HPNN_FUSE_STATE", str(state))
    log.set_verbose(2)
    try:
        # a run at B=8 leaves a mid-run checkpoint behind (kill epoch 3)
        import jax

        from hpnn_tpu.parallel import dp

        real_make = dp.make_gspmd_epoch_fn
        calls = {"n": 0}

        def make_dying(*a, **kw):
            real = real_make(*a, **kw)

            def fn(*fa, **fkw):
                calls["n"] += 1
                if calls["n"] == 3:
                    raise jax.errors.JaxRuntimeError("UNAVAILABLE: simulated")
                return real(*fa, **fkw)

            return fn

        monkeypatch.setattr(dp, "make_gspmd_epoch_fn", make_dying)
        with pytest.raises(jax.errors.JaxRuntimeError):
            batch_mod_local.train_kernel_batched(
                tb._conf_copy(conf), batch_size=8, epochs=4, mesh_spec="2x1")
        monkeypatch.setattr(dp, "make_gspmd_epoch_fn", real_make)
        assert state.exists()
        capsys.readouterr()

        # a B=4 run over the same dir/topology: different effective
        # batch (the 2x1 mesh rounds to the data axis: 8 vs 4 on 2
        # devices stays 8 vs 4), so a different key — no adoption:
        # all 4 epochs train, numbered from 1
        c2 = tb._conf_copy(conf)
        assert batch_mod_local.train_kernel_batched(
            c2, batch_size=4, epochs=4, mesh_spec="2x1")
    finally:
        log.set_verbose(0)
    out = capsys.readouterr().out
    lines = [ln for ln in out.splitlines() if "BATCH EPOCH" in ln]
    assert len(lines) == 4 and "   1 " in lines[0]


def test_profile_trace_writes_xplane(workdir, capsys):
    """--profile DIR wraps the workload in a jax.profiler trace
    (SURVEY.md §5 tracing: the XLA-native replacement for the
    reference's external-profiler hooks) and must leave a trace
    artifact on disk."""
    conf = _conf(workdir)
    tdir = workdir / "trace"
    assert train_nn.main(["--profile", str(tdir), conf]) == 0
    dumped = [p for p in tdir.rglob("*") if p.is_file()]
    assert dumped, "profiler trace directory is empty"
    assert any("xplane" in p.name or p.suffix in (".pb", ".json.gz")
               for p in dumped), [p.name for p in dumped]


def test_fused_round_pallas_body_fallback_and_rekey(workdir, capsys,
                                                    monkeypatch):
    """A Mosaic refusal of the fused-epoch kernel must fall back to the
    lax body mid-round (not burn retries on a deterministic compile
    failure), re-key the checkpoint to the body actually running, and
    complete with the lax round's exact token stream."""
    from hpnn_tpu import config
    from hpnn_tpu.ops import pallas_train
    from hpnn_tpu.train import driver, loop
    from hpnn_tpu.utils import logging as log

    log.set_verbose(2)
    conf_path = _conf(workdir)
    # baseline: plain lax fused round
    conf0 = config.load_conf(conf_path)
    assert driver.train_kernel(conf0)
    want = capsys.readouterr().out

    state = workdir / "round.state"
    monkeypatch.setenv("HPNN_FUSE_STATE", str(state))
    monkeypatch.setattr(loop, "_pallas_epoch_default", lambda w: True)

    def mosaic_refuses(*a, **kw):
        raise ValueError("Mosaic lowering failed (simulated)")

    monkeypatch.setattr(pallas_train, "train_epoch_fused", mosaic_refuses)
    conf = config.load_conf(conf_path)
    assert driver.train_kernel(conf) is True
    captured = capsys.readouterr()
    assert "falling back to the lax body" in captured.err

    def training_lines(s):
        return [ln for ln in s.splitlines() if "TRAINING FILE" in ln]

    assert training_lines(captured.out) == training_lines(want)
    for a, b in zip(conf.kernel.weights, conf0.kernel.weights):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=1e-12)
    assert not state.exists()  # completed round cleans up


def test_fused_round_body_binds_checkpoint_key(workdir, capsys, monkeypatch):
    """A checkpoint written under one epoch body must not be adopted by
    a round running the other body (the two are not bit-identical on
    hardware) — EXCEPT the lax-keyed checkpoint of a fallen-back run,
    which a pallas-default resume adopts AND continues on lax."""
    from hpnn_tpu import config
    from hpnn_tpu.train import driver, loop
    from hpnn_tpu.utils import logging as log

    log.set_verbose(2)
    conf_path = _conf(workdir)
    state = workdir / "round.state"
    monkeypatch.setenv("HPNN_FUSE_STATE", str(state))
    monkeypatch.setenv("HPNN_FUSE_CHUNK", "8")
    # crash a lax round mid-way to leave a lax-keyed checkpoint
    import jax

    real_epoch = loop.train_epoch_lax
    calls = {"n": 0}

    def dying_epoch(*a, **kw):
        calls["n"] += 1
        if calls["n"] == 2:
            raise jax.errors.JaxRuntimeError(
                "UNAVAILABLE: TPU worker process crashed (simulated)")
        return real_epoch(*a, **kw)

    monkeypatch.setattr(loop, "train_epoch_lax", dying_epoch)
    conf = config.load_conf(conf_path)
    with pytest.raises(jax.errors.JaxRuntimeError):
        driver.train_kernel(conf)
    capsys.readouterr()
    assert state.exists()
    done_before = int(np.load(state, allow_pickle=False)["done"])
    assert done_before == 8  # one chunk survived

    # resume with the pallas body as the default: the alt-key probe
    # must adopt the lax checkpoint and stay on lax (train_epoch_fused
    # must never be called)
    monkeypatch.setattr(loop, "train_epoch_lax", real_epoch)
    monkeypatch.setattr(loop, "_pallas_epoch_default", lambda w: True)
    from hpnn_tpu.ops import pallas_train

    def must_not_run(*a, **kw):
        raise AssertionError("resume must stay on the lax body")

    monkeypatch.setattr(pallas_train, "train_epoch_fused", must_not_run)
    conf2 = config.load_conf(conf_path)
    assert driver.train_kernel(conf2) is True
    out = capsys.readouterr().out
    # only the remaining samples were trained by the resume
    assert len([ln for ln in out.splitlines() if "TRAINING FILE" in ln]) == 12
    assert not state.exists()


def test_fused_round_midround_failure_propagates(workdir, capsys,
                                                 monkeypatch):
    """The Mosaic-refusal fallback is gated to the FIRST dispatch
    (chunk_i == 0, same discipline as batch.py's block_i == 0): a
    compile refusal can only surface there — later chunks reuse the
    compiled executable — so a non-UNAVAILABLE error on a LATER chunk
    is a transient fault that must propagate to the crash handler,
    not silently demote the body and re-key the checkpoint."""
    from hpnn_tpu import config
    from hpnn_tpu.ops import pallas_train
    from hpnn_tpu.train import driver, loop
    from hpnn_tpu.utils import logging as log

    log.set_verbose(2)
    conf_path = _conf(workdir)
    monkeypatch.setenv("HPNN_FUSE_CHUNK", "8")
    monkeypatch.setattr(loop, "_pallas_epoch_default", lambda w: True)
    calls = {"n": 0}

    def flaky_fused(*a, **kw):
        calls["n"] += 1
        if calls["n"] == 2:
            raise ValueError("transient device fault (simulated)")
        return loop.train_epoch_lax(*a, **kw)

    monkeypatch.setattr(pallas_train, "train_epoch_fused", flaky_fused)
    conf = config.load_conf(conf_path)
    with pytest.raises(ValueError, match="transient device fault"):
        driver.train_kernel(conf)
    captured = capsys.readouterr()
    assert "falling back to the lax body" not in captured.err
    assert calls["n"] == 2  # chunk 1 trained, chunk 2 raised
