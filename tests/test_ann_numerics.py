"""ANN/SNN numerics vs an independent NumPy oracle.

The oracle below implements the math spec from SURVEY.md §2.3-2.4
directly in NumPy (f64), written independently of the JAX code paths,
so agreement checks both against transcription errors.
"""

import numpy as np
import jax.numpy as jnp
import pytest

from hpnn_tpu.models import ann, snn
from hpnn_tpu.models.kernel import generate

TINY = 1e-14


def np_act(x):
    return 2.0 / (1.0 + np.exp(-x)) - 1.0


def np_forward_ann(ws, x):
    acts = [x]
    for w in ws:
        acts.append(np_act(w @ acts[-1]))
    return acts


def np_forward_snn(ws, x):
    acts = [x]
    for w in ws[:-1]:
        acts.append(np_act(w @ acts[-1]))
    z = ws[-1] @ acts[-1]
    e = np.exp(z - 1.0)
    acts.append(e / (TINY + e.sum()))
    return acts


def np_bp_step_ann(ws, x, t, lr):
    acts = np_forward_ann(ws, x)
    ds = [None] * len(ws)
    o = acts[-1]
    ds[-1] = (t - o) * (-0.5 * (o * o - 1.0))
    for l in range(len(ws) - 2, -1, -1):
        v = acts[l + 1]
        ds[l] = (ws[l + 1].T @ ds[l + 1]) * (-0.5 * (v * v - 1.0))
    return [w + lr * np.outer(d, a) for w, d, a in zip(ws, ds, acts[:-1])]


@pytest.fixture
def setup():
    k, _ = generate(3, 6, [5, 4], 3)
    ws = [np.asarray(w) for w in k.weights]
    rng = np.random.default_rng(0)
    x = rng.normal(size=6)
    t = np.full(3, -1.0)
    t[1] = 1.0
    return ws, x, t


def test_forward_matches_oracle(setup):
    ws, x, t = setup
    jw = tuple(jnp.asarray(w) for w in ws)
    got = ann.forward(jw, jnp.asarray(x))
    want = np_forward_ann(ws, x)
    for g, w in zip(got, want):
        np.testing.assert_allclose(np.asarray(g), w, atol=1e-14)


def test_snn_forward_matches_oracle(setup):
    ws, x, t = setup
    jw = tuple(jnp.asarray(w) for w in ws)
    got = snn.forward(jw, jnp.asarray(x))
    want = np_forward_snn(ws, x)
    np.testing.assert_allclose(np.asarray(got[-1]), want[-1], atol=1e-14)
    assert abs(float(np.asarray(got[-1]).sum()) - 1.0) < 1e-10


def test_error(setup):
    ws, x, t = setup
    out = np_forward_ann(ws, x)[-1]
    got = float(ann.train_error(jnp.asarray(out), jnp.asarray(t)))
    assert abs(got - 0.5 * ((t - out) ** 2).sum()) < 1e-14


def test_snn_error(setup):
    ws, x, t01 = setup
    out = np_forward_snn(ws, x)[-1]
    t = (t01 > 0).astype(float)
    got = float(snn.train_error(jnp.asarray(out), jnp.asarray(t)))
    want = -np.sum(t * np.log(out + TINY)) / out.shape[0]
    assert abs(got - want) < 1e-14


def test_bp_step_matches_oracle(setup):
    ws, x, t = setup
    jw = tuple(jnp.asarray(w) for w in ws)
    acts = ann.forward(jw, jnp.asarray(x))
    new_w, new_acts, dep = ann.train_iteration(jw, acts, jnp.asarray(x), jnp.asarray(t))
    want = np_bp_step_ann(ws, x, t, ann.BP_LEARN_RATE)
    for g, w in zip(new_w, want):
        np.testing.assert_allclose(np.asarray(g), w, atol=1e-14)
    # dEp = Ep - Epr with Epr computed from the UPDATED weights
    ep = 0.5 * ((t - np_forward_ann(ws, x)[-1]) ** 2).sum()
    epr = 0.5 * ((t - np_forward_ann(want, x)[-1]) ** 2).sum()
    assert abs(float(dep) - (ep - epr)) < 1e-12


def test_bpm_step_accumulates_momentum(setup):
    ws, x, t = setup
    jw = tuple(jnp.asarray(w) for w in ws)
    dw = tuple(jnp.zeros_like(w) for w in jw)
    acts = ann.forward(jw, jnp.asarray(x))
    alpha = 0.2
    w1, dw1, acts1, _ = ann.train_iteration_momentum(
        jw, dw, acts, jnp.asarray(x), jnp.asarray(t), alpha
    )
    # first step: dw_new = alpha * lr * outer(d, v); W1 = W + lr*outer
    acts0 = np_forward_ann(ws, x)
    o = acts0[-1]
    d_out = (t - o) * (-0.5 * (o * o - 1.0))
    step = ann.BPM_LEARN_RATE * np.outer(d_out, acts0[-2])
    np.testing.assert_allclose(np.asarray(w1[-1]), ws[-1] + step, atol=1e-14)
    np.testing.assert_allclose(np.asarray(dw1[-1]), alpha * step, atol=1e-14)


def test_snn_output_delta_no_dact(setup):
    ws, x, t01 = setup
    t = (t01 > 0).astype(float)
    jw = tuple(jnp.asarray(w) for w in ws)
    acts = snn.forward(jw, jnp.asarray(x))
    ds = snn.deltas(jw, acts, jnp.asarray(t))
    np.testing.assert_allclose(
        np.asarray(ds[-1]), t - np.asarray(acts[-1]), atol=1e-14
    )


@pytest.mark.parametrize("model,momentum", [
    ("ann", False), ("ann", True), ("snn", False),
])
def test_epoch_scan_matches_sequential(model, momentum):
    """loop.train_epoch_lax == sequential train_sample_lax calls:
    same carried weights, same five per-sample stats (the fused-round
    driver path vs the streaming path)."""
    import jax.numpy as jnp

    from hpnn_tpu.models import kernel as kernel_mod
    from hpnn_tpu.train import loop

    k, _ = kernel_mod.generate(31, 9, [7], 4)
    weights = tuple(jnp.asarray(np.asarray(w), dtype=jnp.float64)
                    for w in k.weights)
    dw0 = tuple(jnp.zeros_like(w) for w in weights) if momentum else ()
    rng = np.random.RandomState(8)
    n = 6
    X = rng.uniform(-1, 1, (n, 9))
    lo = 0.0 if model == "snn" else -1.0
    T = np.full((n, 4), lo)
    T[np.arange(n), rng.randint(0, 4, n)] = 1.0
    kw = dict(model=model, momentum=momentum, min_iter=5, max_iter=80)

    w_seq = weights
    seq_stats = []
    for i in range(n):
        res = loop.train_sample_lax(
            w_seq, dw0, jnp.asarray(X[i]), jnp.asarray(T[i]), 0.2, 1e-6,
            **kw,
        )
        w_seq = res.weights
        seq_stats.append((float(res.ep0), int(res.n_iter), float(res.dep),
                          bool(res.first_ok), bool(res.final_ok)))

    w_fused, stats = loop.train_epoch_lax(
        weights, dw0, jnp.asarray(X), jnp.asarray(T), 0.2, 1e-6, **kw,
    )
    for i in range(n):
        got = (float(stats[0][i]), int(stats[1][i]), float(stats[2][i]),
               bool(stats[3][i]), bool(stats[4][i]))
        assert got == seq_stats[i], f"sample {i}: {got} != {seq_stats[i]}"
    for a, b in zip(w_fused, w_seq):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
