"""Native (C++) runtime vs pure-Python equality.

The native library accelerates the glibc PRNG, the shuffle, text
parsing, and kernel-row formatting; each entry point must agree exactly
with the Python fallback (which itself is validated against real glibc
in tests/test_glibc_random.py).
"""

import numpy as np
import pytest

from hpnn_tpu import native
from hpnn_tpu.utils.glibc_random import RAND_MAX, GlibcRandom

pytestmark = pytest.mark.skipif(
    native.lib() is None, reason="native toolchain unavailable"
)


def test_prng_stream_matches_python():
    import ctypes

    L = native.lib()
    py = GlibcRandom(10958)
    h = L.glibc_new(10958)
    try:
        for _ in range(1000):
            assert L.glibc_next(h) == py.random()
    finally:
        L.glibc_delete(h)


def test_weight_stream_matches_python():
    shapes = [(30, 7), (5, 30)]
    got = native.glibc_weight_stream(1234, shapes)
    rng = GlibcRandom(1234)
    for n, m in shapes:
        sqrt_m = np.sqrt(float(m))
        want = np.array(
            [2.0 * (rng.random() / RAND_MAX - 0.5) / sqrt_m for _ in range(n * m)]
        ).reshape(n, m)
        np.testing.assert_array_equal(got.pop(0), want)


def test_shuffle_matches_python():
    # compute the python answer directly with the raw rejection loop
    rng = GlibcRandom(42)
    n = 257
    taken = [False] * n
    want = []
    for _ in range(n):
        idx = rng.draw_index(n)
        while taken[idx]:
            idx = rng.draw_index(n)
        taken[idx] = True
        want.append(idx)
    got = native.glibc_shuffle(42, n)
    assert got is not None
    assert list(got) == want
    assert sorted(got) == list(range(n))


def test_parse_doubles():
    # GET_DOUBLE walk: each junk char reads as 0.0 and the cursor
    # advances one char, so "junk" yields four zeros before the 7
    got = native.parse_doubles("  1.5 -2.25e1 0.125 junk 7", 10)
    np.testing.assert_array_equal(got, [1.5, -22.5, 0.125, 0, 0, 0, 0, 7.0])
    got = native.parse_doubles("1 2 3 4", 2)
    np.testing.assert_array_equal(got, [1.0, 2.0])


def test_parse_row_matches_python_walk(monkeypatch):
    """Native strtod walk and the pure-Python fallback agree."""
    from hpnn_tpu.fileio.samples import parse_row

    lines = [
        "  1.5 -2.25e1 0.125 junk 7",
        "0.25x 0.5",
        "x 0.5",
        "1.0junk2.0 3",
        "",
        "only 2 number-ish 4x",
        "xxxxx 1.0",  # junk-heavy: each junk char consumes a slot
        "!!!!!!!!!! 9",  # more junk chars than len//2 slots
        "1.0 \u00e9 2.0",  # non-ASCII: UTF-8 bytes are non-graph -> blank
        "\x01 1.5 2.5",  # leading non-graph, non-C-whitespace byte
        "\x7f\x01-3.5 4",  # several leading non-graph bytes
    ]
    assert native.lib() is not None  # else this compares fallback to itself
    natives = [parse_row(line, 8) for line in lines]
    monkeypatch.setenv("HPNN_NO_NATIVE", "1")
    for line, a in zip(lines, natives):
        np.testing.assert_array_equal(a, parse_row(line, 8), err_msg=repr(line))


def test_parse_row_skip_blank_before_first(monkeypatch):
    """SKIP_BLANK runs before the FIRST GET_DOUBLE (ref: src/ann.c:438,
    src/libhpnn.c:1104): a row starting with a non-graph byte that is
    not C whitespace still reads the first real number into slot 0, in
    both the native walk and the Python fallback."""
    from hpnn_tpu.fileio.samples import parse_row

    for env in (None, "1"):
        if env:
            monkeypatch.setenv("HPNN_NO_NATIVE", env)
        np.testing.assert_array_equal(
            parse_row("\x01 1.5 2.5", 2), [1.5, 2.5]
        )
        np.testing.assert_array_equal(
            parse_row("\x7f\x01-3.5 4.0", 2), [-3.5, 4.0]
        )


def test_no_native_env_disables(monkeypatch):
    monkeypatch.setenv("HPNN_NO_NATIVE", "1")
    assert native.lib() is None
    assert native.glibc_shuffle(1, 4) is None
    assert native.parse_doubles("1 2", 2) is None


def test_parse_doubles_bounded_by_text():
    """A huge untrusted count must not drive a huge allocation."""
    got = native.parse_doubles("1.0 2.0", 10**15)
    np.testing.assert_array_equal(got, [1.0, 2.0])


def test_format_row_matches_python():
    rng = np.random.RandomState(0)
    row = rng.uniform(-2, 2, 64)
    want = " ".join("%17.15f" % v for v in row) + "\n"
    assert native.format_row(row) == want


def test_kernel_dump_golden_stability(tmp_path):
    """Native-formatted dump reloads to identical weights."""
    from hpnn_tpu.fileio import kernel_format
    from hpnn_tpu.models import kernel as kernel_mod

    k, _ = kernel_mod.generate(7, 6, [5], 3)
    p = tmp_path / "k.txt"
    with open(p, "w") as fp:
        kernel_format.dump_kernel("g", [np.asarray(w) for w in k.weights], fp)
    name, ws = kernel_format.load_kernel(str(p))
    assert name == "g"
    for a, b in zip(ws, k.weights):
        np.testing.assert_allclose(a, np.asarray(b), atol=1e-15)
