#!/bin/bash
# MNIST SNN variant — 30 rounds, softmax output + cross-entropy
# (ref: /root/reference/tutorials/mnist/opt_mnist.bash).  Run from the
# same directory as tutorial.sh AFTER its data preparation (./mnist).
set -u
N_ROUNDS=${N_ROUNDS:-30}
cd mnist || { echo "run tutorial.sh first (needs ./mnist)"; exit 1; }

cat > mnist_snn.conf <<'EOF'
[name] MNIST
[type] SNN
[init] generate
[seed] 10958
[input] 784
[hidden] 300
[output] 10
[train] BP
[sample_dir] ./samples
[test_dir] ./tests
EOF
sed -e 's/^\[init\].*/[init] kernel.opt/g' -e 's/^\[seed\].*/[seed] 0/g' \
    mnist_snn.conf > cont_mnist_snn.conf

rm -f raw log results; touch raw log
train_nn -v -v ./mnist_snn.conf &> log
run_nn -v -v -v -v ./cont_mnist_snn.conf &> results
NRS=$(grep -c PASS results || true); NOK=$(grep -c ' OK ' log || true)
echo "1 $(awk -v n="$NRS" 'BEGIN{printf "%.1f",100*n/10000}') $(awk -v n="$NOK" 'BEGIN{printf "%.1f",100*n/60000}')" > raw
for IDX in $(seq 2 "$N_ROUNDS"); do
    train_nn -v -v ./cont_mnist_snn.conf &> log
    run_nn -v -v -v -v ./cont_mnist_snn.conf &> results
    NRS=$(grep -c PASS results || true); NOK=$(grep -c ' OK ' log || true)
    echo "$IDX $(awk -v n="$NRS" 'BEGIN{printf "%.1f",100*n/10000}') $(awk -v n="$NOK" 'BEGIN{printf "%.1f",100*n/60000}')" >> raw
    tail -1 raw
done
echo "All DONE!"
