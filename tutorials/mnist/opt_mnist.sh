#!/bin/bash
# MNIST SNN variant — 30 rounds, softmax output + cross-entropy
# (ref: /root/reference/tutorials/mnist/opt_mnist.bash).  Run from the
# same directory as tutorial.sh AFTER its data preparation (./mnist).
#
# Usage: opt_mnist.sh [--batch]
#   --batch  use the TPU minibatch mode (BATCH_SIZE/EPOCHS env override)
#
# Unlike the ANN monitor, the reference's SNN variant divides PASS by
# the test count and OK by the train count correctly
# (ref: opt_mnist.bash:38-44); this port keeps that but takes the
# denominators from the converted sets instead of hardcoding 60k/10k.
set -u
SCRIPT_DIR=$(cd "$(dirname "$0")" && pwd)
N_ROUNDS=${N_ROUNDS:-30}
BATCH_MODE=
for arg in "$@"; do
    case "$arg" in
    --batch) BATCH_MODE=y;;
    esac
done
# fresh-container preflight (see tutorial.sh): offline editable install
command -v train_nn >/dev/null || {
    echo "train_nn not on PATH - installing $SCRIPT_DIR/../.. (offline editable)"
    pip install -e "$SCRIPT_DIR/../.." --no-build-isolation -q || exit 1
}
cd mnist || { echo "run tutorial.sh first (needs ./mnist)"; exit 1; }

cat > mnist_snn.conf <<'EOF'
[name] MNIST
[type] SNN
[init] generate
[seed] 10958
[input] 784
[hidden] 300
[output] 10
[train] BP
[sample_dir] ./samples
[test_dir] ./tests
EOF
sed -e 's/^\[init\].*/[init] kernel.opt/g' -e 's/^\[seed\].*/[seed] 0/g' \
    mnist_snn.conf > cont_mnist_snn.conf

BATCH_ARGS=
[ -n "$BATCH_MODE" ] && BATCH_ARGS="--batch ${BATCH_SIZE:-256} --epochs ${EPOCHS:-5}"

rm -f raw log results; touch raw log
N_TRAIN_FILES=$(ls samples | wc -l)
N_TEST_FILES=$(ls tests | wc -l)
. "$SCRIPT_DIR/monitor.sh"
train_round $BATCH_ARGS ./mnist_snn.conf || { echo "training failed!"; exit 1; }
run_nn -v -v -v -v ./cont_mnist_snn.conf &> results
round_eval 1
for IDX in $(seq 2 "$N_ROUNDS"); do
    rm -f log; touch log
    train_round $BATCH_ARGS ./cont_mnist_snn.conf || { echo "training failed!"; exit 1; }
    run_nn -v -v -v -v ./cont_mnist_snn.conf &> results
    round_eval "$IDX"
done
echo "All DONE!"
