#!/bin/bash
# MNIST ANN tutorial — hpnn-tpu port of the reference tutorial
# (ref: /root/reference/tutorials/mnist/tutorial.bash).
#
# Flow: (optionally) fetch MNIST -> pmnist conversion -> 784-300-10 ANN,
# [train] BP, seed 10958 -> 1 + N_ROUNDS train/eval rounds, appending
#   "<round> <PASS%> <OPT%>"
# to ./mnist/raw (PASS = test top-1 over 10k, OPT = first-try-correct
# over 60k).  NOTE: the reference's monitor swaps the denominators
# (tutorial.bash:179-193 divides PASS by 60000 and OPT by 10000); this
# port divides correctly, so compare raw counts against the reference,
# not its percentages.
#
# Usage: tutorial.sh [--batch] [--synth]
#   --batch  use the TPU minibatch mode (BATCH_SIZE/EPOCHS env override)
#   --synth  no-network mode: generate the deterministic synthetic
#            MNIST-scale dataset (synth_mnist, seed 10958) instead of
#            downloading; same idx container format, same pipeline
set -u
SCRIPT_DIR=$(cd "$(dirname "$0")" && pwd)
N_ROUNDS=${N_ROUNDS:-50}
BATCH_MODE=
SYNTH_MODE=
for arg in "$@"; do
    case "$arg" in
    --batch) BATCH_MODE=y;;
    --synth) SYNTH_MODE=y;;
    esac
done

# fresh-container preflight: the CLIs come from the editable install,
# and pip's default build isolation needs network to fetch setuptools —
# --no-build-isolation builds with the baked-in one instead (README
# "Install (offline)")
command -v train_nn >/dev/null || {
    echo "train_nn not on PATH - installing $SCRIPT_DIR/../.. (offline editable)"
    pip install -e "$SCRIPT_DIR/../.." --no-build-isolation -q || exit 1
}
for tool in pmnist train_nn run_nn; do
    command -v "$tool" >/dev/null || { echo "Can't find $tool!"; exit 1; }
done

if [ ! -f ./mnist/train_images ] && [ -n "$SYNTH_MODE" ]; then
    # generate into a temp dir and move into place so an interrupted
    # generation can't leave a partial ./mnist that a re-run skips
    command -v synth_mnist >/dev/null || { echo "Can't find synth_mnist!"; exit 1; }
    rm -rf mnist.tmp && mkdir -p mnist.tmp
    synth_mnist mnist.tmp --train "${SYNTH_TRAIN:-60000}" --test "${SYNTH_TEST:-10000}" || exit 1
    mkdir -p mnist && mv mnist.tmp/* mnist/ && rmdir mnist.tmp
fi

if [ ! -d ./mnist ]; then
    echo "The MNIST database is required in ./mnist (train_images,"
    echo "train_labels, test_images, test_labels — the renamed idx files)."
    read -r -n 1 -p "Download MNIST database? Y/N " answer; echo
    case $answer in
    [Yy]*)
        mkdir -p mnist/temp && cd mnist/temp || exit 1
        for f in train-images-idx3-ubyte train-labels-idx1-ubyte \
                 t10k-images-idx3-ubyte t10k-labels-idx1-ubyte; do
            wget "https://ossci-datasets.s3.amazonaws.com/mnist/$f.gz" || exit 1
            gunzip "$f.gz"
        done
        mv train-labels-idx1-ubyte ../train_labels
        mv train-images-idx3-ubyte ../train_images
        mv t10k-labels-idx1-ubyte ../test_labels
        mv t10k-images-idx3-ubyte ../test_images
        cd ../.. || exit 1
        ;;
    *) echo "mnist directory is not present!"; exit 1;;
    esac
fi

cd mnist || exit 1
echo "preparing samples"
rm -rf samples tests && mkdir -p samples tests
pmnist samples tests || exit 1

echo "preparing configuration files"
cat > mnist_ann.conf <<'EOF'
[name] MNIST
[type] ANN
[init] generate
[seed] 10958
[input] 784
[hidden] 300
[output] 10
[train] BP
[sample_dir] ./samples
[test_dir] ./tests
EOF
sed -e 's/^\[init\].*/[init] kernel.opt/g' -e 's/^\[seed\].*/[seed] 0/g' \
    mnist_ann.conf > cont_mnist_ann.conf

BATCH_ARGS=
[ -n "$BATCH_MODE" ] && BATCH_ARGS="--batch ${BATCH_SIZE:-256} --epochs ${EPOCHS:-5}"

rm -f raw log results; touch raw log
# denominators from the actual converted sets (not hardcoded 60k/10k,
# which would mis-scale SYNTH_TRAIN/SYNTH_TEST-sized runs)
N_TRAIN_FILES=$(ls samples | wc -l)
N_TEST_FILES=$(ls tests | wc -l)
. "$SCRIPT_DIR/monitor.sh"
# first pass (generate + train + eval)
train_round $BATCH_ARGS ./mnist_ann.conf || { echo "training failed!"; exit 1; }
run_nn -v -v ./cont_mnist_ann.conf &> results
round_eval 0
for IDX in $(seq 1 "$N_ROUNDS"); do
    rm -f log; touch log
    train_round $BATCH_ARGS ./cont_mnist_ann.conf || { echo "training failed!"; exit 1; }
    run_nn -v -v ./cont_mnist_ann.conf &> results
    round_eval "$IDX"
done
echo "All DONE!"
