# Shared round helpers for the MNIST tutorials — sourced by
# tutorial.sh and opt_mnist.sh (both count PASS from run_nn output and
# the OPT numerator from the train log; the batch mode prints no
# per-sample ' OK ', so the last BATCH EPOCH accuracy count stands in,
# format: hpnn_tpu/train/batch.py BATCH EPOCH line).
#
# Expects: $BATCH_MODE, $N_TRAIN_FILES, $N_TEST_FILES, ./log, ./results
# round_eval appends "<round> <PASS%> <OPT%>" to ./raw and echoes it.
. "$SCRIPT_DIR/../lib.sh"
round_eval() {
    NRS=$(grep -c PASS results || true)
    if [ -n "$BATCH_MODE" ]; then
        NOK=$(grep "BATCH EPOCH" log | tail -1 | sed 's/.*(\([0-9]*\)\/.*/\1/')
        NOK=${NOK:-0}
    else
        NOK=$(grep -c ' OK ' log || true)
    fi
    XRS=$(awk -v n="$NRS" -v d="$N_TEST_FILES" 'BEGIN{printf "%.1f", 100*n/d}')
    XOK=$(awk -v n="$NOK" -v d="$N_TRAIN_FILES" 'BEGIN{printf "%.1f", 100*n/d}')
    echo "$1 $XRS $XOK" >> raw
    tail -1 raw
}
