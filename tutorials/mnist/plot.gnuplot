#!/usr/bin/gnuplot
# Live PASS/OPT monitor plot (ref: /root/reference/tutorials/mnist/plot.gnuplot)
set term dumb size 80,30 aspect 1
set tics out
set y2tics
set key below
plot "raw" u 1:2 w lp t "PASS" axis x1y1, "raw" u 1:3 w lp t "OPT" axis x1y2
