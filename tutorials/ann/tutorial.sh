#!/bin/bash
# RRUFF-XRD space-group tutorial — hpnn-tpu port
# (ref: /root/reference/tutorials/ann/tutorial.bash).
#
# Expects the RRUFF XRD data unpacked under ./rruff/dif and ./rruff/raw
# (the reference downloads difs+raw zips from rruff.info).  Converts
# with pdif -i 850 -o 230, then trains an 851-230-230 ANN with BPM
# (alpha=0.2, ref conf: tutorial.bash:9) for 1 + N_ROUNDS rounds; the
# test set is a copy of the samples (ref: tutorial.bash:151-158).
set -u
N_ROUNDS=${N_ROUNDS:-10}
for tool in pdif train_nn run_nn; do
    command -v "$tool" >/dev/null || { echo "Can't find $tool!"; exit 1; }
done
[ -d ./rruff/dif ] && [ -d ./rruff/raw ] || {
    echo "RRUFF data not found: need ./rruff/dif and ./rruff/raw"
    echo "(download the XRD dif + raw archives from rruff.info)"
    exit 1
}
rm -rf samples tests && mkdir -p samples tests
pdif ./rruff -i 850 -o 230 -s ./samples || exit 1
cp ./samples/* ./tests/

cat > xrd.conf <<'EOF'
[name] RRUFF_XRD
[type] ANN
[init] generate
[seed] 0
[input] 851
[hidden] 230
[output] 230
[train] BPM
[sample_dir] ./samples
[test_dir] ./tests
EOF
sed -e 's/^\[init\].*/[init] kernel.opt/g' xrd.conf > cont_xrd.conf

rm -f raw log results; touch raw log
train_nn -v -v -v ./xrd.conf &> log
run_nn -v -v ./cont_xrd.conf &> results
N=$(grep -c 'TESTING' results || true)
NRS=$(grep -c PASS results || true)
echo "0 $NRS/$N" >> raw; tail -1 raw
for IDX in $(seq 1 "$N_ROUNDS"); do
    train_nn -v -v -v ./cont_xrd.conf &> log
    run_nn -v -v ./cont_xrd.conf &> results
    NRS=$(grep -c PASS results || true)
    echo "$IDX $NRS/$N" >> raw; tail -1 raw
done
echo "All DONE!"
