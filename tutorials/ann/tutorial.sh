#!/bin/bash
# RRUFF-XRD space-group tutorial — hpnn-tpu port
# (ref: /root/reference/tutorials/ann/tutorial.bash).
#
# Expects the RRUFF XRD data unpacked under ./rruff/dif and ./rruff/raw
# (the reference downloads difs+raw zips from rruff.info).  Converts
# with pdif -i 850 -o 230, then trains an 851-230-230 ANN with BPM
# (alpha=0.2, ref conf: tutorial.bash:9) for 1 + N_ROUNDS rounds; the
# test set is a copy of the samples (ref: tutorial.bash:151-158).
#
# Usage: tutorial.sh [--batch] [--synth]
#   --batch  use the TPU minibatch mode (BATCH_SIZE/EPOCHS env override)
#   --synth  no-network mode: generate the deterministic synthetic
#            RRUFF-scale dif/raw dataset (synth_rruff, seed 10958)
#            instead of downloading; same container format, same
#            pdif conversion, same pipeline
set -u
SCRIPT_DIR=$(cd "$(dirname "$0")" && pwd)
N_ROUNDS=${N_ROUNDS:-10}
BATCH_MODE=
SYNTH_MODE=
for arg in "$@"; do
    case "$arg" in
    --batch) BATCH_MODE=y;;
    --synth) SYNTH_MODE=y;;
    esac
done

# fresh-container preflight: offline editable install (pip's default
# build isolation needs network — README "Install (offline)")
command -v train_nn >/dev/null || {
    echo "train_nn not on PATH - installing $SCRIPT_DIR/../.. (offline editable)"
    pip install -e "$SCRIPT_DIR/../.." --no-build-isolation -q || exit 1
}
for tool in pdif train_nn run_nn; do
    command -v "$tool" >/dev/null || { echo "Can't find $tool!"; exit 1; }
done

if [ ! -d ./rruff ] && [ -n "$SYNTH_MODE" ]; then
    command -v synth_rruff >/dev/null || { echo "Can't find synth_rruff!"; exit 1; }
    # generate into a temp dir and rename into place so an interrupted
    # generation can't leave a partial ./rruff that a re-run trusts
    rm -rf rruff.tmp && mkdir -p rruff.tmp
    synth_rruff rruff.tmp --per-class "${SYNTH_PER_CLASS:-16}" \
        --seed "${SYNTH_SEED:-10958}" --quirks || exit 1
    mv rruff.tmp rruff
elif [ -n "$SYNTH_MODE" ] && { [ ! -d ./rruff/dif ] || [ ! -d ./rruff/raw ]; }; then
    # never merge synthetic data into a half-present real tree
    echo "partial ./rruff exists (missing dif/ or raw/): remove it or"
    echo "complete it before re-running --synth"
    exit 1
fi

[ -d ./rruff/dif ] && [ -d ./rruff/raw ] || {
    echo "RRUFF data not found: need ./rruff/dif and ./rruff/raw"
    echo "(download the XRD dif + raw archives from rruff.info,"
    echo " or pass --synth for the no-network synthetic dataset)"
    exit 1
}
rm -rf samples tests && mkdir -p samples tests
pdif ./rruff -i 850 -o 230 -s ./samples > pdif.log 2> pdif.err || exit 1
cp ./samples/* ./tests/

cat > xrd.conf <<'EOF'
[name] RRUFF_XRD
[type] ANN
[init] generate
[seed] 0
[input] 851
[hidden] 230
[output] 230
[train] BPM
[sample_dir] ./samples
[test_dir] ./tests
EOF
sed -e 's/^\[init\].*/[init] kernel.opt/g' xrd.conf > cont_xrd.conf

BATCH_ARGS=
# batch defaults tuned for this protocol: the 230-class ±1 one-hot
# dilutes the batch-mean gradient 1:229 and tanh saturates at the
# all-negative plateau — measured: η=0.0005..0.1 stalls at ~1% train
# accuracy, η=0.4 reaches >99.9% by ~1600 epochs (BASELINE.md).  The
# per-sample mode keeps the reference's faithful η (it escapes the
# plateau by converging every sample individually instead).
[ -n "$BATCH_MODE" ] && BATCH_ARGS="--batch ${BATCH_SIZE:-256} --epochs ${EPOCHS:-400} --lr ${BATCH_LR:-0.4}"

. "$SCRIPT_DIR/../lib.sh"

rm -f raw log results; touch raw log
train_round $BATCH_ARGS ./xrd.conf || { echo "training failed!"; exit 1; }
run_nn -v -v ./cont_xrd.conf &> results
N=$(grep -c 'TESTING' results || true)
NRS=$(grep -c PASS results || true)
echo "0 $NRS/$N" >> raw; tail -1 raw
for IDX in $(seq 1 "$N_ROUNDS"); do
    rm -f log; touch log
    train_round $BATCH_ARGS ./cont_xrd.conf || { echo "training failed!"; exit 1; }
    run_nn -v -v ./cont_xrd.conf &> results
    NRS=$(grep -c PASS results || true)
    echo "$IDX $NRS/$N" >> raw; tail -1 raw
done
echo "All DONE!"
