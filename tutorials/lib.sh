# Shared tutorial helpers (sourced by tutorials/*/tutorial.sh and
# tutorials/mnist/opt_mnist.sh from their working directory).

# train_round [args...]: one training round, appended to ./log.
# Both modes checkpoint under HPNN_FUSE_STATE (per-sample rounds per
# chunk, batch rounds per dispatch block) and retry on failure — the
# tunneled TPU worker can crash or hang mid-round and a fresh process
# resumes from the checkpoint.  A hung dispatch is SIGKILLed by the
# per-attempt timeout, and the NEXT resume halves the dispatch size
# when it finds zero progress (per-sample chunk / batch gather-path
# epoch cap; a multi-chip batch round's unit is one epoch and cannot
# shrink further).  Gives up (status 1) after TRAIN_RETRIES attempts
# so callers can abort instead of recording bogus rounds.
train_round() {
    local tries=0
    while [ $tries -lt "${TRAIN_RETRIES:-15}" ]; do
        tries=$((tries+1))
        HPNN_FUSE_STATE="$PWD/round.state" \
            timeout -k 15 "${TRAIN_TIMEOUT:-900}" train_nn -v -v -v "$@" \
            &>> log && return 0
        echo "NN(WARN): training attempt $tries failed; resuming" >> log
        sleep 5
    done
    return 1
}
