# Shared tutorial helpers (sourced by tutorials/*/tutorial.sh and
# tutorials/mnist/opt_mnist.sh from their working directory).

# train_round [args...]: one training round, appended to ./log.
# Batch mode runs once, WITHOUT the timeout/retry machinery — its
# rounds have no resume checkpoint, so killing one would restart it
# from epoch 1 (and its dispatches are short anyway).  Per-sample rounds
# checkpoint per chunk (HPNN_FUSE_STATE) and retry on failure — the
# tunneled TPU worker can crash mid-round and a fresh process resumes
# from the checkpoint.  Gives up (status 1) after TRAIN_RETRIES
# attempts so callers can abort instead of recording bogus rounds.
train_round() {
    if [ -n "$BATCH_MODE" ]; then
        train_nn -v -v -v "$@" &>> log
        return
    fi
    local tries=0
    while [ $tries -lt "${TRAIN_RETRIES:-15}" ]; do
        tries=$((tries+1))
        # the tunneled worker sometimes HANGS a dispatch instead of
        # raising — a per-attempt timeout turns that into a retry that
        # resumes from the chunk checkpoint
        HPNN_FUSE_STATE="$PWD/round.state" \
            timeout -k 15 "${TRAIN_TIMEOUT:-900}" train_nn -v -v -v "$@" \
            &>> log && return 0
        echo "NN(WARN): training attempt $tries failed; resuming" >> log
        sleep 5
    done
    return 1
}
