# Shared tutorial helpers (sourced by tutorials/*/tutorial.sh and
# tutorials/mnist/opt_mnist.sh from their working directory).

# train_round [args...]: one training round, appended to ./log.
# Batch mode runs once (its dispatches are short).  Per-sample rounds
# checkpoint per chunk (HPNN_FUSE_STATE) and retry on failure — the
# tunneled TPU worker can crash mid-round and a fresh process resumes
# from the checkpoint.  Gives up (status 1) after TRAIN_RETRIES
# attempts so callers can abort instead of recording bogus rounds.
train_round() {
    if [ -n "$BATCH_MODE" ]; then
        train_nn -v -v -v "$@" &>> log
        return
    fi
    local tries=0
    while [ $tries -lt "${TRAIN_RETRIES:-15}" ]; do
        tries=$((tries+1))
        HPNN_FUSE_STATE="$PWD/round.state" train_nn -v -v -v "$@" &>> log \
            && return 0
        echo "NN(WARN): training attempt $tries failed; resuming" >> log
        sleep 5
    done
    return 1
}
